"""The dist/ initialization layer, pod identity, and pod-aware keys.

Host-side contracts of the multi-controller path, all testable on one
process: coordinator resolution (the run_pod rules, now in-package),
``pod_info`` precedence (live runtime > launcher env > single), the
``dN.pK`` ProgramStore key segment (byte-identical single-process
grammar; disjoint per-slot keys multi-process), the pod-canonical mesh
construction, addressable-shard placement equivalence, and the
runstore's num_processes config axis.
"""

import json
import pathlib
import subprocess
import sys

import numpy as np
import pytest

import jax

from distributed_sddmm_tpu.dist.init import (
    PodContext, cross_process_probe, pod_info, resolve_init_kwargs,
)
from distributed_sddmm_tpu.programs import keys as keys_mod

REPO = pathlib.Path(__file__).resolve().parents[1]


class TestResolveInitKwargs:
    def test_auto_discovery_is_empty(self, monkeypatch):
        for k in ("DSDDMM_DIST_COORDINATOR", "DSDDMM_DIST_NPROCS",
                  "DSDDMM_DIST_PROC_ID"):
            monkeypatch.delenv(k, raising=False)
        assert resolve_init_kwargs() == {}

    def test_explicit_coordinator(self):
        kw = resolve_init_kwargs("10.0.0.1:1234", 4, 2,
                                 initialization_timeout=30)
        assert kw == {
            "coordinator_address": "10.0.0.1:1234", "num_processes": 4,
            "process_id": 2, "initialization_timeout": 30,
        }

    def test_nprocs_without_coordinator_rejected(self, monkeypatch):
        monkeypatch.delenv("DSDDMM_DIST_COORDINATOR", raising=False)
        with pytest.raises(ValueError, match="coordinator"):
            resolve_init_kwargs(num_processes=2)
        with pytest.raises(ValueError, match="coordinator"):
            resolve_init_kwargs(process_id=1)

    def test_env_fallbacks(self, monkeypatch):
        monkeypatch.setenv("DSDDMM_DIST_COORDINATOR", "h:9")
        monkeypatch.setenv("DSDDMM_DIST_NPROCS", "3")
        monkeypatch.setenv("DSDDMM_DIST_PROC_ID", "1")
        kw = resolve_init_kwargs()
        assert kw["coordinator_address"] == "h:9"
        assert kw["num_processes"] == 3 and kw["process_id"] == 1
        # Explicit arguments beat the env.
        kw = resolve_init_kwargs("x:1", 2, 0)
        assert kw["coordinator_address"] == "x:1"
        assert kw["num_processes"] == 2 and kw["process_id"] == 0


class TestPodInfo:
    def test_single_process_default(self, monkeypatch):
        for k in ("DSDDMM_DIST_COORDINATOR", "DSDDMM_DIST_NPROCS",
                  "DSDDMM_DIST_PROC_ID"):
            monkeypatch.delenv(k, raising=False)
        ctx = pod_info()
        assert ctx == PodContext(1, 0, None)
        assert not ctx.is_multi_host

    def test_env_labels_apply_on_single_process_backend(self, monkeypatch):
        # The test process HAS a live (single-process) backend; the
        # launcher labels must still win so off-pod tooling can produce
        # pod-keyed artifacts.
        monkeypatch.setenv("DSDDMM_DIST_NPROCS", "2")
        monkeypatch.setenv("DSDDMM_DIST_PROC_ID", "1")
        monkeypatch.setenv("DSDDMM_DIST_COORDINATOR", "c:1")
        ctx = pod_info()
        assert (ctx.num_processes, ctx.process_index) == (2, 1)
        assert ctx.coordinator == "c:1" and ctx.is_multi_host
        assert ctx.as_dict() == {
            "num_processes": 2, "process_index": 1, "coordinator": "c:1",
        }

    def test_nprocs_label_without_slot_fails_loudly(self, monkeypatch):
        # Every worker silently claiming p0 would alias per-slot store
        # entries — a launcher that forgets the slot must hear about it.
        monkeypatch.setenv("DSDDMM_DIST_NPROCS", "4")
        monkeypatch.delenv("DSDDMM_DIST_PROC_ID", raising=False)
        with pytest.raises(ValueError, match="DSDDMM_DIST_PROC_ID"):
            pod_info()

    def test_probe_trivially_true_single_process(self):
        ok, err = cross_process_probe()
        assert ok is True and err is None


class TestDistKeySegment:
    def test_single_process_empty(self, monkeypatch):
        monkeypatch.delenv("DSDDMM_DIST_NPROCS", raising=False)
        assert keys_mod.dist_segment() == ""
        assert keys_mod.dist_segment(1, 0) == ""
        assert keys_mod.dist_segment(None, None) == ""

    def test_segment_grammar_round_trip(self):
        seg = keys_mod.dist_segment(4, 3)
        assert seg == "d4.p3"
        assert keys_mod.parse_dist_segment(seg) == {
            "num_processes": 4, "process_index": 3,
        }
        assert keys_mod.parse_dist_segment("b4") is None
        assert keys_mod.parse_dist_segment("d4") is None

    def test_plan_key_byte_identical_without_dist(self):
        old = keys_mod.plan_program_key("fp", "op", "sig", "cpu", code="c0")
        new = keys_mod.plan_program_key("fp", "op", "sig", "cpu", code="c0",
                                        dist="")
        assert old == new
        assert old.count(":") == 5

    def test_plan_key_with_dist_round_trips(self):
        key = keys_mod.plan_program_key(
            "fp", "op", "sig", "tpu", code="c0",
            dist=keys_mod.dist_segment(2, 1),
        )
        assert key.endswith(":d2.p1")
        parsed = keys_mod.parse_plan_key(key)
        assert parsed["num_processes"] == 2
        assert parsed["process_index"] == 1
        assert parsed["dist"] == "d2.p1"
        assert parsed["fingerprint_key"] == "fp"
        # A 7th segment that is not dist-shaped is not a plan key.
        assert keys_mod.parse_plan_key(key + "x") is None
        assert keys_mod.parse_key(key)["family"] == "plan"

    def test_serve_key_dist_segment_round_trips(self):
        key = keys_mod.serve_program_key(
            "alsFoldIn", 4, 8, 16, "cpu", code="c0", params="k3",
            sig="s1", variant="v1.rb8.rm", dist=keys_mod.dist_segment(2, 1),
        )
        assert key.endswith(":d2.p1")
        parsed = keys_mod.parse_serve_key(key)
        assert parsed["num_processes"] == 2 and parsed["process_index"] == 1
        assert parsed["variant"] == "v1.rb8.rm"
        # No dist: byte-identical to the PR 5-13 grammar.
        base = keys_mod.serve_program_key(
            "alsFoldIn", 4, 8, 16, "cpu", code="c0", dist="",
        )
        assert base == keys_mod.serve_program_key(
            "alsFoldIn", 4, 8, 16, "cpu", code="c0",
        )

    def test_bound_strategy_keys_carry_pod_slot(self, monkeypatch, tmp_path):
        """A worker labeled as slot 0 of a 2-pod writes store entries
        under ``:d2.p0`` keys; an unlabeled (single-process) bind of
        the SAME problem writes the classic 6-segment keys — the two
        generations can never alias."""
        from distributed_sddmm_tpu import programs
        from distributed_sddmm_tpu.common import MatMode
        from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
        from distributed_sddmm_tpu.utils.coo import HostCOO

        S = HostCOO.erdos_renyi(48, 40, 4, seed=2, values="normal")

        def run_bound(store_root):
            store = programs.ProgramStore(store_root)
            alg = DenseShift15D(S, R=8, c=2, fusion_approach=2)
            assert programs.bind_strategy(alg, "fpkey", store=store)
            A = alg.dummy_initialize(MatMode.A)
            B = alg.dummy_initialize(MatMode.B)
            alg.fused_spmm(A, B, alg.like_s_values(1.0))
            return [r["key"] for r in store.index()]

        monkeypatch.setenv("DSDDMM_DIST_NPROCS", "2")
        monkeypatch.setenv("DSDDMM_DIST_PROC_ID", "0")
        pod_keys = run_bound(tmp_path / "pod")
        assert pod_keys and all(k.endswith(":d2.p0") for k in pod_keys)

        monkeypatch.delenv("DSDDMM_DIST_NPROCS")
        monkeypatch.delenv("DSDDMM_DIST_PROC_ID")
        solo_keys = run_bound(tmp_path / "solo")
        assert solo_keys and all(
            keys_mod.parse_plan_key(k) is not None
            and "num_processes" not in keys_mod.parse_plan_key(k)
            for k in solo_keys
        )
        assert not set(pod_keys) & set(solo_keys)


class TestPodGrid:
    def test_pod_grid_matches_grid_on_one_host(self):
        from distributed_sddmm_tpu.parallel.mesh import (
            make_grid, make_pod_grid, pod_device_order, process_spans,
        )

        g = make_pod_grid(4, 2, 1, adjacency=1)
        ref = make_grid(4, 2, 1, adjacency=1,
                        devices=pod_device_order())
        assert [d.id for d in g.mesh.devices.flat] == [
            d.id for d in ref.mesh.devices.flat
        ]
        # One host: no axis crosses a process boundary.
        assert process_spans(g) == {
            "rows": False, "cols": False, "layers": False,
        }

    def test_pod_device_order_is_host_major(self):
        from distributed_sddmm_tpu.parallel.mesh import pod_device_order

        devs = pod_device_order()
        keys = [(d.process_index, d.id) for d in devs]
        assert keys == sorted(keys)


class TestPutSharded:
    def test_single_process_bit_identical_to_device_put(self):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from distributed_sddmm_tpu.parallel.sharding import put_sharded

        mesh = Mesh(np.asarray(jax.devices()), ("x",))
        sharding = NamedSharding(mesh, P("x", None))
        host = np.arange(8 * 5, dtype=np.float32).reshape(8, 5)
        a = put_sharded(host, sharding)
        b = jax.device_put(host, sharding)
        assert a.sharding == b.sharding
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestRecordAndRunstoreAxis:
    def test_bench_record_carries_pod_identity(self):
        from distributed_sddmm_tpu.bench.harness import benchmark_algorithm
        from distributed_sddmm_tpu.utils.coo import HostCOO

        S = HostCOO.erdos_renyi(32, 32, 2, seed=0)
        rec = benchmark_algorithm(
            S, "15d_fusion2", None, fused=True, R=8, c=1, trials=1,
            warmup=0,
        )
        assert rec["num_processes"] == 1
        assert rec["process_index"] == 0
        assert "coordinator" not in rec

    def test_multi_host_records_never_pool_into_single(self, tmp_path):
        from distributed_sddmm_tpu.obs.store import RunStore, build_run_doc

        store = RunStore(tmp_path)

        def doc(run_id, num_processes):
            rec = {
                "run_id": run_id, "algorithm": "15d_fusion2",
                "app": "vanilla", "R": 8, "c": 1, "fused": True,
                "kernel": "xla", "elapsed": 1.0,
                "alg_info": {"m": 32, "n": 32, "nnz": 64, "p": 8},
            }
            if num_processes is not None:
                rec["num_processes"] = num_processes
                rec["process_index"] = 0
            return build_run_doc(rec)

        for i in range(3):
            store.put(doc(f"solo-{i}", 1))
        store.put(doc("legacy", None))   # pre-PR-14 record: no field
        store.put(doc("pod", 2))

        pod_doc = store.get("pod")
        matches = {d["run_id"] for d in store.matching(pod_doc)}
        assert matches == set()  # a pod run has no single-process peers

        solo_doc = store.get("solo-2")
        matches = {d["run_id"] for d in store.matching(solo_doc)}
        # None normalizes to 1: legacy docs stay comparable to
        # single-process runs, and the pod run stays out.
        assert matches == {"solo-0", "solo-1", "legacy"}
        row = next(r for r in store.index() if r["run_id"] == "pod")
        assert row["num_processes"] == 2 and row["process_index"] == 0


class TestManifestPodFields:
    def test_manifest_records_pod_identity(self, monkeypatch):
        from distributed_sddmm_tpu.obs import manifest

        monkeypatch.setenv("DSDDMM_DIST_NPROCS", "2")
        monkeypatch.setenv("DSDDMM_DIST_PROC_ID", "1")
        monkeypatch.setenv("DSDDMM_DIST_COORDINATOR", "coord:77")
        m = manifest.build("run-x")
        assert m["num_processes"] == 2
        assert m["process_index"] == 1
        assert m["coordinator"] == "coord:77"
        assert m["env"]["DSDDMM_DIST_COORDINATOR"] == "coord:77"


class TestRunPodDelegation:
    def test_dry_run_through_package_main(self, capsys):
        from distributed_sddmm_tpu.dist.run import main

        assert main(["--dry-run", "er", "12", "4", "15d_fusion2",
                     "8", "1"]) == 0
        out = capsys.readouterr().out
        assert "dry-run ok" in out

    def test_bad_combo_errors(self, capsys):
        from distributed_sddmm_tpu.dist.run import main

        with pytest.raises(SystemExit):
            main(["--dry-run", "--num-processes", "2", "er", "12", "4",
                  "15d_fusion2", "8", "1"])

    def test_admin_port_injection(self, monkeypatch):
        from distributed_sddmm_tpu.dist.run import _inject_admin_port

        monkeypatch.setenv("DSDDMM_POD_ADMIN_BASE", "9100")
        assert _inject_admin_port(["serve", "--app", "als"], 2) == [
            "serve", "--app", "als", "--admin-port", "9102",
        ]
        # Explicit flag wins; non-serve commands untouched.
        assert _inject_admin_port(
            ["serve", "--admin-port", "7"], 2
        ) == ["serve", "--admin-port", "7"]
        assert _inject_admin_port(["er", "12"], 2) == ["er", "12"]
        monkeypatch.delenv("DSDDMM_POD_ADMIN_BASE")
        assert _inject_admin_port(["serve"], 1) == ["serve"]
