"""Auxiliary components: serial ALS oracle, charts, baseline, overlap,
kernel-sweep CLI (SURVEY.md components #17, #19, #23, #24, #29)."""

import json

import numpy as np
import pytest

from distributed_sddmm_tpu.bench.baseline import run_baseline
from distributed_sddmm_tpu.bench.overlap import run_overlap_experiment
from distributed_sddmm_tpu.models.serial_als import SerialALS
from distributed_sddmm_tpu.tools import charts
from distributed_sddmm_tpu.utils.coo import HostCOO


class TestSerialALS:
    def test_residual_decreases_toward_zero(self):
        S = HostCOO.erdos_renyi(120, 90, 5, seed=0)
        als = SerialALS(S, R=8, seed=1)
        r0 = als.compute_residual()
        als.run_cg(3, cg_iters=10)
        r1 = als.compute_residual()
        assert r1 < 0.1 * r0, (r0, r1)

    def test_matches_distributed_als_trajectory(self):
        # Same artificial-groundtruth protocol as DistributedALS: both must
        # drive their residuals down on the same matrix.
        from distributed_sddmm_tpu.models.als import DistributedALS
        from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D

        S = HostCOO.erdos_renyi(96, 80, 4, seed=2)
        serial = SerialALS(S, R=8, seed=0)
        serial.run_cg(2)
        dist = DistributedALS(DenseShift15D(S, R=8, c=1))
        dist.run_cg(2)
        assert serial.compute_residual() < 0.5
        assert dist.compute_residual() < 0.5

    def test_explicit_ground_truth(self):
        S = HostCOO.erdos_renyi(60, 60, 4, seed=3)
        obs = np.random.default_rng(0).standard_normal(S.nnz) * 0.01
        als = SerialALS(S, R=6, artificial_groundtruth=False, ground_truth_vals=obs)
        r0 = als.compute_residual()
        als.run_cg(2)
        assert als.compute_residual() < r0


class TestBaseline:
    def test_schema_and_positive_throughput(self, tmp_path):
        S = HostCOO.erdos_renyi(256, 256, 8, seed=0)
        out = str(tmp_path / "base.jsonl")
        rec = run_baseline(S, R=32, iters=3, output_file=out)
        assert rec["overall_throughput"] > 0
        assert rec["nnz"] == S.nnz and rec["r"] == 32
        on_disk = json.loads(open(out).read().strip())
        assert on_disk == pytest.approx(rec, rel=1e-9) or on_disk == rec


class TestOverlap:
    def test_runs_on_mesh(self, tmp_path):
        rec = run_overlap_experiment(block=64, steps_work=2, trials=2,
                                     output_file=str(tmp_path / "o.jsonl"))
        assert rec["p"] >= 1
        assert rec["interleaved_ms"] > 0 and rec["serialized_ms"] > 0


class TestCharts:
    def test_end_to_end(self, tmp_path):
        pytest.importorskip("matplotlib")
        records = [
            {
                "algorithm": "15d_fusion2", "fused": True, "R": 64,
                "overall_throughput": 10.0, "c": 1,
                "perf_stats": {"fusedSpMM": 1.2},
                "alg_info": {"c": 1},
            },
            {
                "algorithm": "15d_sparse", "fused": False, "R": 64,
                "overall_throughput": 12.0, "c": 1,
                "perf_stats": {"sddmmA": 0.5, "spmmA": 0.9},
                "alg_info": {"c": 1},
            },
        ]
        src = tmp_path / "r.jsonl"
        with open(src, "w") as f:
            for r in records:
                f.write(json.dumps(r) + "\n")
        rc = charts.main([str(src), "-o", str(tmp_path / "charts")])
        assert rc == 0
        assert (tmp_path / "charts" / "benchmark.png").exists()
        winners = json.loads((tmp_path / "charts" / "winners.json").read_text())
        assert winners == {"R=64,c=1": "15d_sparse"}

    def test_empty_input(self, tmp_path):
        src = tmp_path / "empty.jsonl"
        src.write_text("")
        assert charts.main([str(src)]) == 1

    def test_kernels_mode(self, tmp_path):
        records = [
            {"kernel": "xla", "logM": 14, "npr": 32, "R": 128,
             "fused_pair_gflops": 16.0},
            {"kernel": "pallas-bf16", "logM": 14, "npr": 32, "R": 128,
             "bm": 512, "bn": 512, "group": 4, "fused_pair_gflops": 80.0},
            # second record for the same (point, kernel): best one wins
            {"kernel": "pallas-bf16", "logM": 14, "npr": 32, "R": 128,
             "bm": 256, "bn": 512, "group": 1, "fused_pair_gflops": 40.0},
            {"kernel": "pallas-bf16", "logM": 16, "npr": 32, "R": 128,
             "bm": 512, "bn": 512, "group": 4, "fused_pair_gflops": 70.0},
        ]
        src = tmp_path / "k.jsonl"
        with open(src, "w") as f:
            for r in records:
                f.write(json.dumps(r) + "\n")
        rc = charts.main([str(src), "--kernels", "-o", str(tmp_path / "out")])
        assert rc == 0
        assert (tmp_path / "out" / "kernels.png").exists()
        # A harness-records file in --kernels mode has nothing to plot.
        src2 = tmp_path / "h.jsonl"
        src2.write_text(json.dumps({"algorithm": "15d_sparse",
                                    "overall_throughput": 1.0}) + "\n")
        assert charts.main([str(src2), "--kernels",
                            "-o", str(tmp_path / "out2")]) == 1


class TestKernelSweepCLI:
    def test_tiny_sweep_smoke(self, capsys):
        from distributed_sddmm_tpu.bench.kernels import run_kernel_benchmark

        recs = run_kernel_benchmark(
            log_m_values=[8], nnz_per_row_values=[4], r_values=[8],
            kernels=("xla",), trials=1,
        )
        assert len(recs) == 1
        assert recs[0]["sddmm_gflops"] > 0 and recs[0]["spmm_gflops"] > 0
        assert "GFLOP" in capsys.readouterr().out


def test_run_pod_dry_run(capsys):
    """The pod runner's wiring is validated without a pod: forwarded bench
    args must parse and the resolved initialize() kwargs print
    (`/root/reference/jobscript.sh` analog, SURVEY component #28)."""
    import importlib.util
    import pathlib

    spec = importlib.util.spec_from_file_location(
        "run_pod",
        pathlib.Path(__file__).resolve().parents[1] / "scripts" / "run_pod.py",
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    assert mod.main(["--dry-run", "er", "16", "32", "15d_fusion2", "128", "2"]) == 0
    out = capsys.readouterr().out
    assert "dry-run ok" in out

    import pytest

    with pytest.raises(SystemExit):
        mod.main(["--dry-run", "er", "not-an-int"])


class TestForceFetch:
    """utils.platform.force_fetch — the execution barrier every timed
    region relies on (tunneled backends ignore block_until_ready)."""

    def test_scalar_per_leaf(self):
        import jax.numpy as jnp

        from distributed_sddmm_tpu.utils.platform import force_fetch

        out = force_fetch((jnp.full((3, 2), 2.0), jnp.ones((4,), jnp.int32)))
        assert out == 3.0  # first element of each leaf

    def test_empty_and_non_array_leaves(self):
        import jax.numpy as jnp

        from distributed_sddmm_tpu.utils.platform import force_fetch

        assert force_fetch((jnp.zeros((0, 5)), "label", None, 7)) == 0.0

    def test_tracer_safe(self):
        import jax
        import jax.numpy as jnp

        from distributed_sddmm_tpu.utils.platform import force_fetch

        def f(x):
            force_fetch(x)  # must be a no-op under trace, not a crash
            return (x * 2).sum()

        assert float(jax.grad(f)(jnp.ones((3,)))[0]) == 2.0
