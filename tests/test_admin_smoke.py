"""The CI entry point for the admin-surface smoke: live endpoints in
miniature (ephemeral port, real HTTP scrapes, burn flip, fault storm)."""

import json
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]


def test_admin_smoke_script(tmp_path):
    out_file = tmp_path / "smoke.json"
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "admin_smoke.py"),
         "-o", str(out_file)],
        capture_output=True, text=True, timeout=540,
        env={"PATH": "/usr/bin:/bin:/usr/local/bin", "HOME": "/tmp"},
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    rep = json.loads(out_file.read_text())
    assert rep["ok"] is True
    by_name = {c["name"]: c for c in rep["checks"]}
    assert set(by_name) == {
        "scrape", "health_ready", "burn_flip", "faulted",
    }
    # The contract bits, re-asserted here so a smoke refactor cannot
    # silently stop checking them: exposition agrees with the engine's
    # own stats, buckets are cumulative, readiness flips on burn while
    # liveness does not, and a persistent fault storm never kills the
    # surface.
    assert all(by_name["scrape"]["agree"].values())
    assert by_name["scrape"]["hist_cumulative_ok"] is True
    assert by_name["burn_flip"]["readyz"] == 503
    assert by_name["burn_flip"]["healthz"] == 200
    assert by_name["burn_flip"]["burn_rate"] > 1.0
    assert by_name["faulted"]["healthz_under_fault"] == 200
    assert by_name["faulted"]["degraded_delta"] > 0
