"""The CI smoke entry point: cost-model-only autotune on the CPU mesh."""

import json
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]


def test_autotune_smoke_script(tmp_path):
    out_file = tmp_path / "smoke.json"
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "autotune_smoke.py"),
         "-o", str(out_file)],
        capture_output=True, text=True, timeout=300,
        env={"PATH": "/usr/bin:/bin:/usr/local/bin", "HOME": "/tmp",
             "DSDDMM_PLAN_CACHE": str(tmp_path / "cache")},
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    rep = json.loads(out_file.read_text())
    assert rep["ok"] is True
    by_name = {r["probe"]["name"]: r for r in rep["probes"]}
    assert len(by_name) == 6 and not any("error" in r for r in rep["probes"])
    # The OOM corner emerged chunk-routed (never crash, never prune-away).
    heavy = by_name["heavy_corner"]
    assert heavy["chunk_routed"] is True
    # Cost-model-only mode answers quickly even cold; warm hits are
    # well under a second (the cache-hit latency bar).
    for r in rep["probes"]:
        assert r["warm_s"] < 1.0
