"""Chrome trace export: schema mapping + the merge->export round trip.

The tentpole contract pinned here: ``bench trace-merge`` a two-shard
serve trace, ``bench trace-export`` the merged file, and every request
chain ``tracereport.request_chains`` reconstructs appears as a
connected ``s``/``t``/``f`` flow in the Chrome JSON — monotonic
timestamps within each flow, disjoint flow ids across requests, every
event valid per the Chrome trace-event schema, and B/E span pairs
balanced per thread lane.
"""

import json

import numpy as np
import pytest

from distributed_sddmm_tpu.bench import cli
from distributed_sddmm_tpu.obs import trace, traceexport
from distributed_sddmm_tpu.tools import tracereport

_REQUIRED_BY_PH = {
    "M": ("name", "pid", "args"),
    "B": ("name", "pid", "tid", "ts"),
    "E": ("pid", "tid", "ts"),
    "X": ("name", "pid", "tid", "ts", "dur"),
    "i": ("name", "pid", "tid", "ts", "s"),
    "s": ("name", "cat", "id", "pid", "tid", "ts"),
    "t": ("name", "cat", "id", "pid", "tid", "ts"),
    "f": ("name", "cat", "id", "pid", "tid", "ts"),
}


def _assert_valid_chrome(chrome: dict) -> None:
    assert chrome["displayTimeUnit"] == "ms"
    for ev in chrome["traceEvents"]:
        ph = ev.get("ph")
        assert ph in _REQUIRED_BY_PH, f"unknown phase {ph!r}: {ev}"
        for field in _REQUIRED_BY_PH[ph]:
            assert field in ev, f"{ph} event missing {field!r}: {ev}"
        if "ts" in ev:
            assert ev["ts"] >= 0


def _flows(chrome: dict) -> dict:
    out: dict = {}
    for ev in chrome["traceEvents"]:
        if ev.get("cat") == "request" and ev.get("ph") in ("s", "t", "f"):
            out.setdefault(ev["id"], []).append(ev)
    return out


@pytest.fixture(autouse=True)
def _clean_tracer(monkeypatch):
    monkeypatch.delenv("DSDDMM_TRACE", raising=False)
    trace.disable()
    yield
    trace.disable()


def _synthetic_trace(tmp_path, name="t.jsonl"):
    """One hand-written schema-valid trace: nested spans sharing
    timestamps (the tie-ordering case) + one instant event."""
    recs = [
        {"type": "begin", "schema": 1, "run_id": "syn", "t0_epoch": 100.0,
         "pid": 77},
        # Parent and child open at the same instant; child closes when
        # parent does — exactly the tie the exporter must order.
        {"type": "span", "name": "child", "id": 2, "parent": 1, "tid": 9,
         "t0": 1.0, "t1": 2.0, "dur_s": 1.0, "attrs": {"k": 1}},
        {"type": "span", "name": "parent", "id": 1, "parent": None,
         "tid": 9, "t0": 1.0, "t1": 2.0, "dur_s": 1.0, "attrs": {}},
        {"type": "event", "name": "mark", "id": 3, "parent": 1, "tid": 9,
         "t": 1.5, "attrs": {"x": "y"}},
    ]
    p = tmp_path / name
    p.write_text("".join(json.dumps(r) + "\n" for r in recs))
    return p


class TestChromeMapping:
    def test_spans_are_balanced_be_pairs_with_tie_ordering(self, tmp_path):
        out, chrome = traceexport.write_chrome(_synthetic_trace(tmp_path))
        _assert_valid_chrome(chrome)
        seq = [e for e in chrome["traceEvents"] if e.get("ph") in "BE"]
        # Open shallowest-first, close deepest-first: parent B, child B,
        # child E, parent E — despite all four sharing two timestamps.
        assert [(e.get("name"), e["ph"]) for e in seq] == [
            ("parent", "B"), ("child", "B"), (None, "E"), (None, "E"),
        ]
        b_child = [e for e in seq if e.get("name") == "child"][0]
        assert b_child["args"] == {"k": 1}
        assert b_child["ts"] == pytest.approx(1.0e6)

    def test_events_become_instants_and_meta_names_lanes(self, tmp_path):
        _out, chrome = traceexport.write_chrome(_synthetic_trace(tmp_path))
        inst = [e for e in chrome["traceEvents"] if e.get("ph") == "i"]
        assert len(inst) == 1 and inst[0]["name"] == "mark"
        metas = [e for e in chrome["traceEvents"] if e.get("ph") == "M"]
        names = {e["name"] for e in metas}
        assert {"process_name", "thread_name"} <= names
        proc = [e for e in metas if e["name"] == "process_name"][0]
        assert "syn" in proc["args"]["name"]
        assert "77" in proc["args"]["name"]

    def test_default_output_path_and_cli(self, tmp_path, capsys):
        p = _synthetic_trace(tmp_path)
        rc = cli.main(["trace-export", str(p)])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["exported"].endswith("t.chrome.json")
        chrome = json.loads((tmp_path / "t.chrome.json").read_text())
        _assert_valid_chrome(chrome)

    def test_invalid_trace_exits_2(self, tmp_path, capsys):
        bad = tmp_path / "bad.jsonl"
        bad.write_text('{"type": "span"}\n')
        rc = cli.main(["trace-export", str(bad)])
        assert rc == 2
        assert "trace-export failed" in capsys.readouterr().err

    def test_missing_trace_exits_2(self, tmp_path, capsys):
        rc = cli.main(["trace-export", str(tmp_path / "nope.jsonl")])
        assert rc == 2


# --------------------------------------------------------------------- #
# The tentpole round trip: two-shard serve trace -> merge -> export
# --------------------------------------------------------------------- #


@pytest.fixture(scope="module")
def als_workload():
    from distributed_sddmm_tpu.models.als import DistributedALS
    from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
    from distributed_sddmm_tpu.serve import ALSFoldInTopK
    from distributed_sddmm_tpu.utils.coo import HostCOO

    S = HostCOO.erdos_renyi(64, 48, 4, seed=11, values="normal")
    alg = DenseShift15D(S, R=8, c=1, fusion_approach=2)
    model = DistributedALS(alg, S_host=S)
    model.run_cg(1, cg_iters=2)
    return ALSFoldInTopK(model, k=4, item_buckets=(4,))


@pytest.fixture(scope="module")
def merged_serve_trace(als_workload, tmp_path_factory):
    """Two serve shards (distinct tracer origins, overlapping request
    ids — the shard-key case) merged through the CLI."""
    from distributed_sddmm_tpu.serve import ServingEngine

    tmp = tmp_path_factory.mktemp("shards")
    trace.disable()
    shard_paths = []
    for i in (0, 1):
        tr = trace.enable(tmp / f"shard{i}.jsonl")
        engine = ServingEngine(
            als_workload, max_batch=4, max_depth=32, max_wait_ms=2.0
        )
        rng = np.random.default_rng(20 + i)
        engine.start(warmup=False)
        try:
            reqs = [engine.submit(als_workload.sample_payload(rng))
                    for _ in range(4)]
            for r in reqs:
                r.result(timeout_s=60.0)
        finally:
            engine.stop()
        trace.disable()
        shard_paths.append(str(tr.path))
    out = tmp / "merged.jsonl"
    rc = cli.main(["trace-merge", *shard_paths, "-o", str(out)])
    assert rc == 0
    return out


class TestMergedRoundTrip:
    def test_every_request_chain_is_a_connected_flow(
        self, merged_serve_trace, tmp_path
    ):
        loaded = tracereport.load_trace(merged_serve_trace, strict=True)
        chains = tracereport.request_chains(loaded)
        assert chains["complete"] == 8  # 4 requests x 2 shards
        assert chains["inconsistent"] == 0

        out = tmp_path / "merged.chrome.json"
        rc = cli.main(["trace-export", str(merged_serve_trace),
                       "-o", str(out)])
        assert rc == 0
        chrome = json.loads(out.read_text())
        _assert_valid_chrome(chrome)

        flows = _flows(chrome)
        # One flow per complete chain, ids disjoint by construction of
        # the dict; each flow is the full s -> t -> f triple with
        # monotonic timestamps (enqueue before batch before reply).
        assert len(flows) == chains["complete"]
        for fid, evs in flows.items():
            assert [e["ph"] for e in evs] == ["s", "t", "f"]
            ts = [e["ts"] for e in evs]
            assert ts == sorted(ts)
            assert evs[-1].get("bp") == "e"
        # Flow endpoints land on both shards' process lanes.
        assert {e["pid"] for f in flows.values() for e in f} == {1, 2}

    def test_lanes_one_process_per_shard(self, merged_serve_trace):
        loaded = tracereport.load_trace(merged_serve_trace, strict=True)
        chrome = traceexport.to_chrome(loaded)
        procs = [e for e in chrome["traceEvents"]
                 if e.get("ph") == "M" and e["name"] == "process_name"]
        assert len(procs) == 2
        assert len(chrome["metadata"]["shards"]) == 2

    def test_be_balanced_and_timestamps_monotone_per_lane(
        self, merged_serve_trace
    ):
        loaded = tracereport.load_trace(merged_serve_trace, strict=True)
        chrome = traceexport.to_chrome(loaded)
        depth: dict = {}
        for ev in chrome["traceEvents"]:
            ph = ev.get("ph")
            if ph == "B":
                depth[(ev["pid"], ev["tid"])] = depth.get(
                    (ev["pid"], ev["tid"]), 0) + 1
            elif ph == "E":
                key = (ev["pid"], ev["tid"])
                depth[key] = depth.get(key, 0) - 1
                assert depth[key] >= 0, "E without matching B"
        assert all(v == 0 for v in depth.values())
        assert chrome["metadata"]["spans"] == len(loaded["spans"])
        assert chrome["metadata"]["request_flows"] == 8


class TestFleetFlows:
    """PR-19: cross-process fleet links become Chrome flow arrows
    (category ``fleet``) from the router attempt span to the replica
    records the merge re-parented onto it."""

    def _merged_fleet_trace(self):
        return {
            "begin": {"run_id": "merged-x", "t0_epoch": 0.0,
                      "shards": [{"run_id": "rt"}, {"run_id": "rp"}],
                      "fleet_links": 2},
            "spans": [
                {"type": "span", "name": "fleet:attempt", "id": 2,
                 "parent": 1, "tid": 1, "t0": 0.0, "t1": 0.3,
                 "dur_s": 0.3, "shard": "rt",
                 "attrs": {"fleet_req": "fr-1"}},
                # In-process nesting preserved: fleet_parent recorded
                # as an attr only, parent points elsewhere -> NO arrow.
                {"type": "span", "name": "serve:batch", "id": 5,
                 "parent": 4, "tid": 1, "t0": 0.1, "t1": 0.2,
                 "dur_s": 0.1, "shard": "rp",
                 "attrs": {"fleet_parent": 2}},
            ],
            "events": [
                # True re-parent point (parent == fleet_parent): arrow.
                {"type": "event", "name": "serve:enqueue", "id": 4,
                 "parent": 2, "tid": 1, "t": 0.05, "shard": "rp",
                 "attrs": {"req": 0, "fleet_req": "fr-1",
                           "fleet_parent": 2}},
            ],
            "errors": [],
        }

    def test_fleet_links_become_flow_arrows(self):
        chrome = traceexport.to_chrome(self._merged_fleet_trace())
        flows = [e for e in chrome["traceEvents"]
                 if e.get("cat") == "fleet"]
        # One s/f pair for the enqueue re-parent, none for the batch
        # span whose parent is in-process.
        assert [e["ph"] for e in sorted(flows, key=lambda e: e["ts"])] \
            == ["s", "f"]
        assert all(e["args"]["fleet_req"] == "fr-1" for e in flows)
        assert chrome["metadata"]["fleet_flows"] == 1

    def test_untraced_fleet_metadata_zero(self):
        doc = self._merged_fleet_trace()
        for r in doc["spans"] + doc["events"]:
            r["attrs"].pop("fleet_parent", None)
        chrome = traceexport.to_chrome(doc)
        assert chrome["metadata"]["fleet_flows"] == 0
        assert not [e for e in chrome["traceEvents"]
                    if e.get("cat") == "fleet"]
