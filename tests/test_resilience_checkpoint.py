"""Checkpoint store recovery + the kill-and-resume acceptance test."""

import json
import pathlib

import numpy as np
import pytest

from distributed_sddmm_tpu.models.als import DistributedALS
from distributed_sddmm_tpu.models.gat import GAT, GATLayer
from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
from distributed_sddmm_tpu.resilience import (
    CheckpointStore, FaultPlan, FaultSpec, InjectedFault, fault_plan,
)
from distributed_sddmm_tpu.resilience import checkpoint as ckpt_mod
from distributed_sddmm_tpu.utils.coo import HostCOO


def _arrays(scale=1.0):
    rng = np.random.default_rng(0)
    return {"A": (rng.random((6, 4)) * scale).astype(np.float32),
            "B": (rng.random((5, 4)) * scale).astype(np.float32)}


# --------------------------------------------------------------------- #
# Store unit behavior
# --------------------------------------------------------------------- #


def test_save_load_roundtrip_bit_exact(tmp_path):
    store = CheckpointStore(tmp_path)
    arrs = _arrays()
    store.save(3, arrs, meta={"kind": "als"})
    step, got, meta = store.load_latest()
    assert step == 3 and meta == {"kind": "als"}
    for k in arrs:
        assert np.array_equal(got[k], arrs[k])  # bit-exact, not allclose


def test_corrupt_latest_npz_scans_back_one_step(tmp_path):
    store = CheckpointStore(tmp_path)
    store.save(1, _arrays(1.0))
    store.save(2, _arrays(2.0))
    p = store._step_path(2)
    p.write_bytes(p.read_bytes()[:40])  # torn write
    step, got, _ = store.load_latest()
    assert step == 1
    assert np.array_equal(got["A"], _arrays(1.0)["A"])


def test_corrupt_latest_pointer_falls_back_to_scan(tmp_path):
    store = CheckpointStore(tmp_path)
    store.save(5, _arrays())
    (tmp_path / "latest.json").write_text("{torn")
    step, _, _ = store.load_latest()
    assert step == 5


def test_digest_mismatch_rejects_garbled_npz(tmp_path):
    """A write fault that garbles the npz between digest and disk must be
    caught by the digest check, then recovered by scan-back."""
    store = CheckpointStore(tmp_path)
    store.save(1, _arrays(1.0))
    with fault_plan(FaultPlan(
        [FaultSpec(site="write:step_00000002.npz", kind="garble", at=(0,))]
    )):
        store.save(2, _arrays(2.0))
    step, got, _ = store.load_latest()
    assert step == 1  # garbled step 2 never serves
    assert np.array_equal(got["A"], _arrays(1.0)["A"])


def test_schema_version_rollback_reads_as_miss(tmp_path, monkeypatch):
    """A future-schema latest.json (rolled-back binary scenario) must not
    half-parse: the pointer is ignored, the scan still serves the data
    files it can actually read."""
    store = CheckpointStore(tmp_path)
    store.save(1, _arrays())
    rec = json.loads((tmp_path / "latest.json").read_text())
    rec["schema_version"] = ckpt_mod.SCHEMA_VERSION + 1
    (tmp_path / "latest.json").write_text(json.dumps(rec))
    step, _, meta = store.load_latest()
    assert step == 1 and meta == {}  # served via scan, not the foreign pointer


def test_empty_store_returns_none(tmp_path):
    assert CheckpointStore(tmp_path / "nonexistent").load_latest() is None


def test_prune_keeps_last_k(tmp_path):
    store = CheckpointStore(tmp_path, keep_last=2)
    for s in range(1, 6):
        store.save(s, _arrays())
    assert store.steps() == [4, 5]
    assert store.load_latest()[0] == 5


# --------------------------------------------------------------------- #
# ALS kill-and-resume (acceptance criterion: bit-identical factors)
# --------------------------------------------------------------------- #


def _make_als():
    S = HostCOO.erdos_renyi(48, 32, 5, seed=0)
    return DistributedALS(
        DenseShift15D(S, R=8, c=2, fusion_approach=2), seed=0, S_host=S
    )


def test_als_kill_and_resume_bit_identical(tmp_path):
    """A fault plan crashes ALS mid-run; resuming from the last checkpoint
    must converge to factors BIT-identical to an uninterrupted run —
    checkpointed state is exact and the remaining steps are deterministic
    functions of it."""
    als = _make_als()
    als.run_cg(4, cg_iters=5)
    want_A, want_B = np.asarray(als.A), np.asarray(als.B)

    store = CheckpointStore(tmp_path)
    crashed = _make_als()
    with fault_plan(FaultPlan(
        [FaultSpec(site="als:step", kind="error", at=(2,))]
    )):
        with pytest.raises(InjectedFault):
            crashed.run_cg(4, cg_iters=5, checkpoint=store, checkpoint_every=1)
    assert store.load_latest()[0] == 2  # steps 1 and 2 landed before the crash

    resumed = _make_als()
    resumed.run_cg(4, cg_iters=5, checkpoint=store, checkpoint_every=1,
                   resume=True)
    assert np.array_equal(np.asarray(resumed.A), want_A)
    assert np.array_equal(np.asarray(resumed.B), want_B)
    assert resumed.compute_residual() < 1e-2


def test_als_resume_with_empty_store_is_fresh_start(tmp_path):
    als = _make_als()
    als.run_cg(1, cg_iters=3, checkpoint=CheckpointStore(tmp_path),
               resume=True)
    assert als.A is not None


@pytest.mark.slow  # kill-and-resume's bit-identity assertion subsumes
# the step bookkeeping this pins; kept for -m slow runs.
def test_als_mid_cg_crash_resumes_from_last_step(tmp_path):
    """Crash INSIDE the CG inner loop (not between steps): the interrupted
    step never checkpoints, resume re-runs it from the last completed one."""
    store = CheckpointStore(tmp_path)
    crashed = _make_als()
    # Step 0: 2 half-steps x 3 iters = 6 cg_iter calls; crash in step 1's A
    # half-step, iteration 1 (global call #7).
    with fault_plan(FaultPlan(
        [FaultSpec(site="als:cg_iter", kind="error", at=(7,))]
    )):
        with pytest.raises(InjectedFault):
            crashed.run_cg(3, cg_iters=3, checkpoint=store, checkpoint_every=1)
    assert store.load_latest()[0] == 1

    resumed = _make_als()
    resumed.run_cg(3, cg_iters=3, checkpoint=store, checkpoint_every=1,
                   resume=True)
    want = _make_als()
    want.run_cg(3, cg_iters=3)
    assert np.array_equal(np.asarray(resumed.A), np.asarray(want.A))
    assert np.array_equal(np.asarray(resumed.B), np.asarray(want.B))


def test_als_ignores_foreign_store_kind(tmp_path):
    store = CheckpointStore(tmp_path)
    store.save(9, {"w_0_0": np.zeros((4, 4), np.float32)}, meta={"kind": "gat"})
    als = _make_als()
    assert als.restore_checkpoint(store) == 0  # GAT weights never become factors


# --------------------------------------------------------------------- #
# GAT parameter checkpoints
# --------------------------------------------------------------------- #


def test_gat_weights_roundtrip(tmp_path):
    S = HostCOO.erdos_renyi(32, 32, 4, seed=1)
    alg = DenseShift15D(S, R=8, c=1, fusion_approach=2)
    layers = [GATLayer(input_features=8, features_per_head=8, num_heads=2)]
    gat = GAT(layers, alg, seed=3)
    store = CheckpointStore(tmp_path)
    gat.save_checkpoint(store)

    gat2 = GAT([GATLayer(input_features=8, features_per_head=8, num_heads=2)],
               alg, seed=99)  # different init
    assert not np.array_equal(
        np.asarray(gat.layers[0].weights[0]),
        np.asarray(gat2.layers[0].weights[0]),
    )
    assert gat2.load_checkpoint(store)
    for j in range(2):
        assert np.array_equal(
            np.asarray(gat.layers[0].weights[j]),
            np.asarray(gat2.layers[0].weights[j]),
        )
    # Restored params drive an identical forward pass.
    out1 = np.asarray(gat.forward())
    out2 = np.asarray(gat2.forward())
    assert np.array_equal(out1, out2)


def test_gat_rejects_foreign_or_missing_checkpoint(tmp_path):
    S = HostCOO.erdos_renyi(32, 32, 4, seed=1)
    alg = DenseShift15D(S, R=8, c=1, fusion_approach=2)
    gat = GAT([GATLayer(input_features=8, features_per_head=8, num_heads=2)],
              alg, seed=3)
    empty = CheckpointStore(tmp_path / "empty")
    assert not gat.load_checkpoint(empty)
    als_store = CheckpointStore(tmp_path / "als")
    als_store.save(1, {"A": np.zeros((4, 4), np.float32)}, meta={"kind": "als"})
    assert not gat.load_checkpoint(als_store)
