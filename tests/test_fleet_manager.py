"""Fleet-manager lifecycle tests against the stub worker
(``tests/_fleet_worker.py``) — a real OS process speaking the full
replica contract (admin surface, SIGTERM → record → exit 0) with no
engine behind it, so spawn/reap/respawn semantics are exercised on real
processes in milliseconds.
"""

import os
import sys
import time

import pytest

from distributed_sddmm_tpu.fleet import FleetManager

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "_fleet_worker.py")
READY_S = 30.0


def _argv(name, port, role):
    return [sys.executable, WORKER, "--admin-port", str(port),
            "--name", name, "--role", role]


def _crash_argv(name, port, role):
    return _argv(name, port, role) + ["--crash-after", "0.1"]


@pytest.fixture
def manager():
    mgr = FleetManager(_argv, tuner_canary=False)
    yield mgr
    mgr.stop_all(timeout_s=10.0)


class TestLifecycle:
    def test_spawn_wait_ready_snapshot(self, manager):
        rep = manager.spawn()
        assert rep.name == "r0" and rep.generation == 0
        assert manager.wait_ready(READY_S)
        snaps = manager.snapshots()
        assert snaps["r0"]["name"] == "r0"
        assert snaps["r0"]["buckets"]["inner"] == [4, 8]

    def test_drain_collects_record(self, manager):
        manager.spawn()
        manager.spawn()
        assert manager.wait_ready(READY_S)
        record = manager.drain("r0")
        assert record["name"] == "r0"
        assert record["app"] == "fleet-worker-stub"
        assert manager.get("r0").rc == 0
        assert record in manager.records
        assert [r.name for r in manager.replicas()] == ["r1"]
        assert manager.losses == 0

    def test_kill_poll_respawn_bumps_generation(self, manager):
        manager.spawn()
        manager.spawn()
        assert manager.wait_ready(READY_S)
        manager.kill("r1")
        deadline = time.monotonic() + 10.0
        while manager.get("r1").alive and time.monotonic() < deadline:
            time.sleep(0.05)
        replaced = manager.respawn_dead()
        assert [r.name for r in replaced] == ["r1"]
        assert replaced[0].generation == 1
        assert manager.losses == 1  # unplanned death is a loss...
        assert manager.wait_ready(READY_S, names=["r1"])
        assert manager.spawns == 3
        # ...and a SIGKILLed replica leaves no record behind.
        assert all(r.get("name") != "r1" for r in manager.records)

    def test_wait_ready_fails_fast_on_dead_replica(self):
        mgr = FleetManager(_crash_argv, tuner_canary=False)
        try:
            mgr.spawn()
            t0 = time.monotonic()
            assert mgr.wait_ready(timeout_s=60.0) is False
            assert time.monotonic() - t0 < 30.0  # no full-timeout wait
        finally:
            mgr.stop_all(timeout_s=10.0)

    def test_stop_all_reaps_everything(self, manager):
        manager.spawn()
        manager.spawn()
        assert manager.wait_ready(READY_S)
        records = manager.stop_all(timeout_s=10.0)
        assert {r["name"] for r in records} == {"r0", "r1"}
        assert manager.replicas() == []


class TestGrayFailure:
    def test_quarantine_lifecycle(self, manager):
        manager.spawn()
        manager.spawn()
        assert manager.wait_ready(READY_S)
        replacement = manager.quarantine(
            "r0", reason="byzantine reply mismatch (audit)",
            evidence={"disagreed_with": ["r1"]},
        )
        # Drained out of routing, alive for autopsy.
        rep = manager.get("r0")
        assert rep.quarantined is True and rep.alive is True
        assert [r.name for r in manager.replicas()] == ["r1", "r2"]
        assert "r0" in [r.name for r in
                        manager.replicas(include_quarantined=True)]
        # Ledger + warm replacement under a FRESH name (the
        # quarantined slot still exists for the autopsy).
        assert replacement is not None and replacement.name == "r2"
        assert manager.quarantines == 1
        (entry,) = manager.quarantine_log
        assert entry["name"] == "r0"
        assert "byzantine" in entry["reason"]
        assert manager.wait_ready(READY_S, names=["r2"])
        # Teardown still collects the quarantined replica's record.
        records = manager.stop_all(timeout_s=10.0)
        assert "r0" in {r["name"] for r in records}
        assert manager.get("r0").rc == 0

    def test_quarantine_is_idempotent(self, manager):
        manager.spawn()
        manager.spawn()
        assert manager.wait_ready(READY_S)
        assert manager.quarantine("r0", respawn=False) is None
        assert manager.quarantine("r0") is None  # already quarantined
        assert manager.quarantines == 1
        assert manager.spawns == 2  # respawn=False spawned nothing

    def test_wedged_replica_freezes_and_teardown_reaps(self, manager):
        """Satellite 6: SIGSTOP freezes the admin surface, and
        ``stop_all`` SIGCONTs before SIGTERM so a wedged replica still
        drains promptly with a record instead of leaking a stopped
        process or losing the drain to the kill timeout."""
        from distributed_sddmm_tpu.obs.httpexp import fetch_json

        manager.spawn()
        manager.spawn()
        assert manager.wait_ready(READY_S)
        port = manager.get("r0").port
        manager.wedge("r0")
        assert manager.get("r0").wedged is True
        with pytest.raises(OSError):
            fetch_json("127.0.0.1", port, "/readyz", timeout_s=1.0)
        t0 = time.monotonic()
        records = manager.stop_all(timeout_s=10.0)
        assert time.monotonic() - t0 < 8.0  # no drain-timeout kill
        assert {r["name"] for r in records} == {"r0", "r1"}
        rep = manager.get("r0")
        assert rep.alive is False and rep.rc == 0 and not rep.wedged

    def test_unwedge_restores_the_admin_surface(self, manager):
        from distributed_sddmm_tpu.obs.httpexp import fetch_json

        manager.spawn()
        assert manager.wait_ready(READY_S)
        port = manager.get("r0").port
        manager.wedge("r0")
        manager.unwedge("r0")
        assert manager.get("r0").wedged is False
        body = fetch_json("127.0.0.1", port, "/readyz", timeout_s=5.0)
        assert body.get("ready") is True


class TestTunerDiscipline:
    def test_exactly_one_canary(self):
        mgr = FleetManager(_argv, tuner_canary=True)
        try:
            a = mgr.spawn()
            b = mgr.spawn()
            assert a.tuner is True and b.tuner is False
            assert mgr.wait_ready(READY_S)
        finally:
            records = mgr.stop_all(timeout_s=10.0)
        armed = {r["name"]: r["tuner_armed"] for r in records}
        assert armed == {"r0": True, "r1": False}

    def test_canary_respawn_rearms(self):
        mgr = FleetManager(_argv, tuner_canary=True)
        try:
            mgr.spawn()
            mgr.spawn()
            assert mgr.wait_ready(READY_S)
            mgr.kill("r0")  # the canary dies...
            deadline = time.monotonic() + 10.0
            while mgr.get("r0").alive and time.monotonic() < deadline:
                time.sleep(0.05)
            (rep,) = mgr.respawn_dead()
            # ...and its replacement is the one that re-arms.
            assert rep.name == "r0" and rep.tuner is True
        finally:
            mgr.stop_all(timeout_s=10.0)

    def test_rollout_replaces_non_canary_one_at_a_time(self):
        mgr = FleetManager(_argv, tuner_canary=True)
        try:
            mgr.spawn()
            mgr.spawn()
            mgr.spawn()
            assert mgr.wait_ready(READY_S)
            rolled = mgr.rollout(ready_timeout_s=READY_S)
            assert rolled == ["r1", "r2"]  # canary r0 untouched
            assert mgr.get("r0").generation == 0
            assert mgr.get("r1").generation == 1
            assert mgr.get("r2").generation == 1
            # The drained pre-rollout replicas handed in records.
            assert {r["name"] for r in mgr.records} == {"r1", "r2"}
        finally:
            mgr.stop_all(timeout_s=10.0)
