"""Worker for the multi-process pod tests (test_multiprocess.py) and
the elastic kill-and-recover drill (test_dist_elastic.py).

**Pod mode** (default): one "host" of a 2-process CPU pod — initializes
jax.distributed against the shared coordinator (the same wiring
``scripts/run_pod.py`` / ``dist/run.py`` performs on a real pod), runs
the cross-process ``device_put`` capability probe
(``dist.init.cross_process_probe``) and EMITS THE PROBE RESULT in its
record; when the backend supports cross-process placement it continues
into the full distributed computation (strategy over the global
4-device mesh) and reports device-computed fingerprints. The parent
test keys its strictness on the probe instead of an unconditional
xfail, so the pod test runs strict the day the jax backend supports it.

**Elastic mode** (``--elastic``): one worker generation of the elastic
drill. The DATA partition is fixed at ``--nshards`` (the original pod
size); this worker owns shards ``{s : s % nprocs == pid}`` and runs
``--steps`` deterministic damped-iteration steps per shard over the
PARTITIONED ingest path (``dist.ingest.erdos_renyi_partitioned`` — no
worker materializes the full matrix), checkpointing each shard's state
per step (``resilience.checkpoint.CheckpointStore``) and resuming from
scan-back when checkpoints exist. The kill sites (``mp_worker:start``,
``mp_worker:post_compute`` — fired once per step, AFTER compute and
BEFORE the checkpoint save) model losing a worker between a completed
step and its checkpoint.

Usage: python tests/_mp_worker.py <process_id> <coordinator_port>
         [--elastic --nprocs K --nshards S --steps N
          --checkpoint-dir DIR --generation G]
"""

import argparse
import json
import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

os.environ["JAX_PLATFORMS"] = "cpu"
# Append to (not overwrite) inherited XLA_FLAGS so harness-exported memory or
# debug flags keep applying; only the device-count flag is forced to 2 (the
# suite's conftest exports 8, and last-occurrence wins in XLA's parser).
_flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if not f.startswith("--xla_force_host_platform_device_count")]
os.environ["XLA_FLAGS"] = " ".join(
    _flags + ["--xla_force_host_platform_device_count=2"])

import jax

jax.config.update("jax_platforms", "cpu")


def _parse_argv():
    ap = argparse.ArgumentParser()
    ap.add_argument("pid", type=int)
    ap.add_argument("port", type=int)
    ap.add_argument("--elastic", action="store_true")
    ap.add_argument("--nprocs", type=int, default=2,
                    help="live workers this generation (elastic mode)")
    ap.add_argument("--nshards", type=int, default=2,
                    help="fixed data-partition count (original pod size)")
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--generation", type=int, default=0)
    return ap.parse_args()


def elastic_main(args) -> None:
    """One elastic generation: partitioned ingest, per-shard step loop
    with scan-back resume, per-step checkpoint, kill sites live."""
    import numpy as np

    from distributed_sddmm_tpu.dist import ingest
    from distributed_sddmm_tpu.obs import trace as obs_trace
    from distributed_sddmm_tpu.resilience import faults
    from distributed_sddmm_tpu.resilience.checkpoint import CheckpointStore

    faults.maybe_kill("mp_worker:start")
    obs_trace.event(
        "mp_worker:start", process=args.pid, pid_os=os.getpid(),
        generation=args.generation, elastic=True,
    )

    import jax.numpy as jnp

    step_fn = jax.jit(lambda x, r: 0.5 * x + r)

    shards = [s for s in range(args.nshards) if s % args.nprocs == args.pid]
    fingerprints = {}
    for s in shards:
        # Partitioned ingest: this worker parses ONLY shard s's block
        # rows of the (deterministic, p-invariant) generated matrix.
        shard = ingest.erdos_renyi_partitioned(
            96, 80, 4, args.nshards, s, seed=5, values="normal",
            chunk_edges=64,
        )
        rows_local = shard.row1 - shard.row0
        drive = np.zeros(max(rows_local, 1))
        if shard.nnz:
            np.add.at(drive, shard.coo.rows - shard.row0, shard.coo.vals)
        store = CheckpointStore(
            pathlib.Path(args.checkpoint_dir) / f"shard{s}"
        )
        latest = store.load_latest()
        if latest is not None:
            start, arrays, _meta = latest
            x = jnp.asarray(arrays["x"])
            start += 1  # the checkpoint holds the COMPLETED step
        else:
            start, x = 0, jnp.zeros_like(jnp.asarray(drive))
        r = jnp.asarray(drive)
        for t in range(start, args.steps):
            with obs_trace.span(
                "elastic:step", shard=s, step=t, process=args.pid,
                generation=args.generation,
            ):
                x = step_fn(x, r)
                x.block_until_ready()
            # Post-compute, pre-checkpoint: a kill here loses exactly
            # this step's checkpoint — scan-back recovery recomputes it.
            faults.maybe_kill("mp_worker:post_compute")
            store.save(t, {"x": np.asarray(x)})
        fingerprints[str(s)] = float(np.sum(np.asarray(x, np.float64) ** 2))

    obs_trace.event("mp_worker:done", process=args.pid,
                    generation=args.generation)
    obs_trace.disable()  # flush the shard before the result line
    print(json.dumps({
        "pid": args.pid, "generation": args.generation,
        "shards": fingerprints, "steps": args.steps,
    }), flush=True)


def pod_main(args) -> None:
    pid, port = args.pid, args.port

    # Fault hooks (env-activated via DSDDMM_FAULTS, e.g. a "kill" spec at
    # site mp_worker:start) — the resilience fault-matrix test preempts one
    # worker here and asserts the parent detects it without hanging.
    from distributed_sddmm_tpu.resilience import faults

    faults.maybe_kill("mp_worker:start")

    # Per-process trace shard: when the parent traces (DSDDMM_TRACE in the
    # inherited env — a traced parent exports its shard directory), this
    # worker writes its own <run_id>.jsonl shard there; `bench trace-merge`
    # offset-aligns the shards back into one timeline. The event both
    # activates the env-configured tracer and stamps which process this
    # shard belongs to.
    from distributed_sddmm_tpu.obs import trace as obs_trace

    obs_trace.event("mp_worker:start", process=pid, pid_os=os.getpid())

    from distributed_sddmm_tpu.dist.init import cross_process_probe, initialize

    initialize(
        coordinator=f"127.0.0.1:{port}", num_processes=2, process_id=pid,
        initialization_timeout=int(
            os.environ.get("DSDDMM_MP_INIT_TIMEOUT", 300)
        ),
    )
    assert jax.device_count() == 4 and jax.local_device_count() == 2

    # Capability probe: can THIS backend place a cross-process global
    # array? The verdict is emitted IMMEDIATELY (its own line, before
    # any strategy code runs) so the parent can tell "died before the
    # probe" (environment xfail) from "probe passed, strategy code
    # crashed" (a hard regression once a backend supports the pod
    # path); the final record carries it again.
    probe_ok, probe_err = cross_process_probe()
    print(json.dumps({"pid": pid, "probe": True, "probe_ok": probe_ok}),
          flush=True)
    if not probe_ok:
        obs_trace.event("mp_worker:done", process=pid, probe_ok=False)
        obs_trace.disable()
        print(json.dumps({
            "pid": pid, "probe_ok": False, "probe_error": probe_err,
        }), flush=True)
        return

    import jax.numpy as jnp

    from distributed_sddmm_tpu.common import MatMode
    from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
    from distributed_sddmm_tpu.utils.coo import HostCOO

    # Identical host data on every process (SPMD ingest contract: the same
    # seed everywhere; parallel/sharding.put_sharded places only the
    # addressable shards).
    S = HostCOO.erdos_renyi(96, 80, 4, seed=5, values="normal")
    alg = DenseShift15D(S, R=16, c=2, fusion_approach=2)
    A = alg.dummy_initialize(MatMode.A)
    B = alg.dummy_initialize(MatMode.B)
    out, mid = alg.fused_spmm(A, B, alg.like_s_values(1.0))

    # Device-side fingerprints: jitted global reductions produce replicated
    # scalars every process can fetch (host gathers would need non-local
    # shards).
    fp_out = float(jnp.sum(out * out))
    fp_mid = float(jnp.sum(mid * mid))
    # Post-compute preemption point: collectives are done, the result is
    # about to be reported — a kill here models losing a worker between a
    # completed step and its checkpoint.
    faults.maybe_kill("mp_worker:post_compute")
    obs_trace.event("mp_worker:done", process=pid)
    obs_trace.disable()  # flush the shard before the result line
    print(json.dumps({
        "pid": pid, "probe_ok": True, "fp_out": fp_out, "fp_mid": fp_mid,
    }), flush=True)


def main() -> None:
    args = _parse_argv()
    if args.elastic:
        elastic_main(args)
    else:
        pod_main(args)


if __name__ == "__main__":
    main()
