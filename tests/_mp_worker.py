"""Worker for the two-process jax.distributed test (test_multiprocess.py).

Each invocation is one "host" of a 2-process CPU pod: it initializes
jax.distributed against the shared coordinator (the same wiring
`scripts/run_pod.py` performs on a real pod), builds a strategy over the
GLOBAL 4-device mesh (2 processes x 2 local devices), runs distributed ops,
and prints device-computed fingerprints as one JSON line. The parent test
compares the two processes' fingerprints against each other and against the
same strategy program run on a single-process 4-device mesh.

Usage: python tests/_mp_worker.py <process_id> <coordinator_port>
"""

import json
import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

os.environ["JAX_PLATFORMS"] = "cpu"
# Append to (not overwrite) inherited XLA_FLAGS so harness-exported memory or
# debug flags keep applying; only the device-count flag is forced to 2 (the
# suite's conftest exports 8, and last-occurrence wins in XLA's parser).
_flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if not f.startswith("--xla_force_host_platform_device_count")]
os.environ["XLA_FLAGS"] = " ".join(
    _flags + ["--xla_force_host_platform_device_count=2"])

import jax

jax.config.update("jax_platforms", "cpu")


def main() -> None:
    pid = int(sys.argv[1])
    port = int(sys.argv[2])

    # Fault hooks (env-activated via DSDDMM_FAULTS, e.g. a "kill" spec at
    # site mp_worker:start) — the resilience fault-matrix test preempts one
    # worker here and asserts the parent detects it without hanging.
    from distributed_sddmm_tpu.resilience import faults

    faults.maybe_kill("mp_worker:start")

    # Per-process trace shard: when the parent traces (DSDDMM_TRACE in the
    # inherited env — a traced parent exports its shard directory), this
    # worker writes its own <run_id>.jsonl shard there; `bench trace-merge`
    # offset-aligns the shards back into one timeline. The event both
    # activates the env-configured tracer and stamps which process this
    # shard belongs to.
    from distributed_sddmm_tpu.obs import trace as obs_trace

    obs_trace.event("mp_worker:start", process=pid, pid_os=os.getpid())

    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid,
        initialization_timeout=int(os.environ.get("DSDDMM_MP_INIT_TIMEOUT", 300)),
    )
    assert jax.device_count() == 4 and jax.local_device_count() == 2

    import jax.numpy as jnp

    from distributed_sddmm_tpu.common import MatMode
    from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
    from distributed_sddmm_tpu.utils.coo import HostCOO

    # Identical host data on every process (SPMD ingest contract: the same
    # seed everywhere, device_put places only the addressable shards).
    S = HostCOO.erdos_renyi(96, 80, 4, seed=5, values="normal")
    alg = DenseShift15D(S, R=16, c=2, fusion_approach=2)
    A = alg.dummy_initialize(MatMode.A)
    B = alg.dummy_initialize(MatMode.B)
    out, mid = alg.fused_spmm(A, B, alg.like_s_values(1.0))

    # Device-side fingerprints: jitted global reductions produce replicated
    # scalars every process can fetch (host gathers would need non-local
    # shards).
    fp_out = float(jnp.sum(out * out))
    fp_mid = float(jnp.sum(mid * mid))
    # Post-compute preemption point: collectives are done, the result is
    # about to be reported — a kill here models losing a worker between a
    # completed step and its checkpoint.
    faults.maybe_kill("mp_worker:post_compute")
    obs_trace.event("mp_worker:done", process=pid)
    obs_trace.disable()  # flush the shard before the result line
    print(json.dumps({"pid": pid, "fp_out": fp_out, "fp_mid": fp_mid}),
          flush=True)


if __name__ == "__main__":
    main()
