"""Structural HLO gate for ``--fusion overlap`` (the tier-1 acceptance
check): the overlap-built 1.5D dense-shift fused program, AOT-compiled
for a real v5e TPU topology (``jax.experimental.topologies``, no chips
needed), must schedule ``collective-permute-start``/``-done`` BRACKETING
the per-step local kernel — ``async_pairs >= 1`` and
``loop_body_overlaps_compute`` — i.e. the ring hop is in flight behind
the compute, the reference's hand-built ``BufferPair`` behavior.

The compile runs in a subprocess: libtpu reads its environment once at
first init, and on machines without TPU instance metadata the topology
lookup stalls in ~minute-long metadata retries unless
``TPU_SKIP_MDS_QUERY=1`` is exported first (this container's case).

The scanner itself is also unit-tested on synthetic HLO so a regression
in the gate's own parsing cannot masquerade as scheduler evidence.
"""

import json
import os
import pathlib
import subprocess
import sys

from distributed_sddmm_tpu.bench.overlap import scan_overlap_hlo

REPO = pathlib.Path(__file__).resolve().parents[1]

_PROBE = """
import json, sys
sys.path.insert(0, {repo!r})
from distributed_sddmm_tpu.utils.platform import force_cpu_platform
force_cpu_platform(n_devices=8, replace=True)
from distributed_sddmm_tpu.bench.overlap import fusion_overlap_hlo_report
print("RESULT " + json.dumps(fusion_overlap_hlo_report(overlap=True)))
"""


def test_fusion_overlap_v5e_hlo_gate():
    env = dict(os.environ)
    env.update({
        "TPU_SKIP_MDS_QUERY": "1",
        "DSDDMM_PROGRAMS": "0",
        "DSDDMM_RUNSTORE": "0",
        "PYTHONPATH": str(REPO),
    })
    proc = subprocess.run(
        [sys.executable, "-c", _PROBE.format(repo=str(REPO))],
        capture_output=True, text=True, timeout=540, env=env,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    line = [l for l in proc.stdout.splitlines() if l.startswith("RESULT ")]
    assert line, proc.stdout[-2000:]
    rec = json.loads(line[0][len("RESULT "):])
    assert rec["fusion"] == "overlap" and rec["topology"] == "v5e:2x4"
    assert rec["is_scheduled"] is True
    assert rec["async_pairs"] >= 1, rec
    assert rec["loop_body_overlaps_compute"] is True, rec


# --------------------------------------------------------------------- #
# The scanner's own contract, on synthetic scheduled-HLO text
# --------------------------------------------------------------------- #

_HLO_OVERLAPPED = """\
HloModule jit_prog, is_scheduled=true

%body (arg: f32[8]) -> f32[8] {
  %start = collective-permute-start(f32[8] %x), source_target_pairs={{0,1}}
  %f = f32[8] fusion(f32[8] %y), kind=kLoop
  %done = f32[8] collective-permute-done(%start)
  ROOT %r = f32[8] add(%f, %done)
}
"""

_HLO_SEQUENTIAL = """\
HloModule jit_prog, is_scheduled=true

%body (arg: f32[8]) -> f32[8] {
  %f = f32[8] fusion(f32[8] %y), kind=kLoop
  %start = collective-permute-start(f32[8] %x), source_target_pairs={{0,1}}
  %done = f32[8] collective-permute-done(%start)
  ROOT %r = f32[8] add(%f, %done)
}
"""

_HLO_SYNC = """\
HloModule jit_prog, is_scheduled=true

%body (arg: f32[8]) -> f32[8] {
  %p = f32[8] collective-permute(f32[8] %x), source_target_pairs={{0,1}}
  ROOT %f = f32[8] fusion(%p), kind=kLoop
}
"""


def test_scanner_detects_bracketed_compute():
    rec = scan_overlap_hlo(_HLO_OVERLAPPED)
    assert rec == {"is_scheduled": True, "async_pairs": 1,
                   "loop_body_overlaps_compute": True}


def test_scanner_rejects_unbracketed_compute():
    rec = scan_overlap_hlo(_HLO_SEQUENTIAL)
    assert rec["async_pairs"] == 1
    assert rec["loop_body_overlaps_compute"] is False


def test_scanner_counts_zero_pairs_for_sync_permute():
    rec = scan_overlap_hlo(_HLO_SYNC)
    assert rec["async_pairs"] == 0
    assert rec["loop_body_overlaps_compute"] is False
