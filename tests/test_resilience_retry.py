"""Retry/timeout/backoff utility + guard primitives."""

import random
import threading
import time

import numpy as np
import pytest

from distributed_sddmm_tpu.resilience import (
    Backoff, CallTimeout, call_with_timeout, retry_call,
)
from distributed_sddmm_tpu.resilience.guards import CGGuard, NumericalFault, guard_output


def test_call_with_timeout_returns_value_and_propagates_errors():
    assert call_with_timeout(lambda: 42, 5.0) == 42
    with pytest.raises(KeyError):
        call_with_timeout(lambda: {}["x"], 5.0)


def test_call_with_timeout_expires():
    t0 = time.monotonic()
    with pytest.raises(CallTimeout):
        call_with_timeout(lambda: time.sleep(10), 0.2, label="hang")
    assert time.monotonic() - t0 < 5.0


def test_call_with_timeout_works_off_main_thread():
    """The property the SIGALRM path lacked: a bounded call from a worker
    thread (signal.setitimer only arms on the main thread)."""
    result = {}

    def worker():
        try:
            call_with_timeout(lambda: time.sleep(10), 0.2)
        except CallTimeout:
            result["timed_out"] = True

    t = threading.Thread(target=worker)
    t.start()
    t.join(timeout=30)
    assert result.get("timed_out") is True


def test_backoff_jitter_bounds_and_determinism():
    bo = Backoff(base_s=1.0, factor=2.0, jitter=0.5, rng=random.Random(7))
    delays = [bo.delay(i) for i in range(4)]
    for i, d in enumerate(delays):
        assert 1.0 * 2 ** i < d <= 1.5 * 2 ** i
    bo2 = Backoff(base_s=1.0, factor=2.0, jitter=0.5, rng=random.Random(7))
    assert delays == [bo2.delay(i) for i in range(4)]


def test_backoff_default_rng_desynchronizes():
    """Two default-constructed backoffs (the fleet case) must not produce
    identical schedules — that re-collision is the bug jitter fixes."""
    a = Backoff(base_s=1.0, jitter=0.5)
    b = Backoff(base_s=1.0, jitter=0.5, rng=random.Random(a.rng.random()))
    assert [a.delay(i) for i in range(4)] != [b.delay(i) for i in range(4)]


def test_retry_call_recovers_then_exhausts():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise TimeoutError("flaky")
        return "ok"

    assert retry_call(
        flaky, retries=3, backoff=Backoff(base_s=0.0, jitter=0.0),
        sleep=lambda s: None,
    ) == "ok"
    assert calls["n"] == 3

    def dead():
        raise TimeoutError("dead")

    with pytest.raises(TimeoutError):
        retry_call(dead, retries=2, backoff=Backoff(base_s=0.0, jitter=0.0),
                   sleep=lambda s: None)


def test_retry_call_give_up_on_wins():
    calls = {"n": 0}

    def bad():
        calls["n"] += 1
        raise ValueError("deterministic")

    with pytest.raises(ValueError):
        retry_call(bad, retries=5, retry_on=(Exception,),
                   give_up_on=(ValueError,), sleep=lambda s: None)
    assert calls["n"] == 1  # no retry budget burned on a deterministic error


def test_retry_call_elapsed_cap_stops_early():
    sleeps = []
    clock = iter(range(0, 10000, 100))

    def dead():
        raise TimeoutError("dead")

    with pytest.raises(TimeoutError):
        retry_call(
            dead, retries=10,
            backoff=Backoff(base_s=1.0, jitter=0.0, max_elapsed_s=150.0),
            sleep=sleeps.append, clock=lambda: float(next(clock)),
        )
    assert len(sleeps) < 10


# --------------------------------------------------------------------- #
# Guards
# --------------------------------------------------------------------- #


def test_guard_output_raise_and_repair():
    import jax.numpy as jnp

    clean = jnp.ones((4, 4))
    assert guard_output("op", clean, mode="raise") is clean
    poisoned = clean.at[0, 0].set(jnp.nan)
    with pytest.raises(NumericalFault, match="op"):
        guard_output("op", poisoned, mode="raise")
    repaired = guard_output("op", poisoned, mode="repair")
    assert bool(jnp.isfinite(repaired).all())


def test_guard_output_handles_pytrees_and_numpy():
    x = np.ones(4)
    y = np.array([1.0, np.inf])
    with pytest.raises(NumericalFault):
        guard_output("pair", (x, y), mode="raise")
    rx, ry = guard_output("pair", (x, y), mode="repair")
    assert np.isfinite(ry).all() and np.array_equal(rx, x)


def test_cg_guard_trips_on_growth_not_noise():
    g = CGGuard(growth_tol=10.0, patience=2)
    # Healthy convergence with float noise: never trips.
    for rs in [100.0, 50.0, 51.0, 20.0, 19.0, 1.0]:
        assert not g.update(rs)
    # Sustained explosion: trips after `patience` strikes.
    g2 = CGGuard(growth_tol=10.0, patience=2)
    assert not g2.update(10.0)
    assert not g2.update(500.0)   # strike 1
    assert g2.update(5000.0)      # strike 2 -> diverged


def test_cg_guard_trips_instantly_on_nonfinite():
    g = CGGuard()
    assert g.update(float("nan"))
    g2 = CGGuard()
    assert g2.update(float("inf"))
