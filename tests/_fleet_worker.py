"""Stub fleet replica for manager tests: the full process contract of
``bench serve --serve-http`` (admin surface, SIGTERM → record-on-stdout
→ exit 0) with no engine and no jax, so a spawn costs milliseconds.

Run as::

    python tests/_fleet_worker.py --admin-port 12345 --name r0 [--crash-after S]

The admin surface is a real :class:`AdminServer` in exporter mode —
``/healthz`` / ``/readyz`` / ``/snapshot`` behave as the manager and
router expect. On SIGTERM the worker prints its serving record as the
last stdout line (the ``last_json_line`` reap convention) and exits 0.
``--crash-after`` simulates a crash-on-boot / mid-life death: exit 17
with no record after that many seconds.
"""

import argparse
import json
import os
import signal
import sys
import threading
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--admin-port", type=int, required=True)
    ap.add_argument("--name", default="worker")
    ap.add_argument("--role", default="serve")
    ap.add_argument("--crash-after", type=float, default=None)
    args = ap.parse_args()

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from distributed_sddmm_tpu.obs.httpexp import AdminServer

    served = {"n": 0}

    def snapshot():
        return {
            "name": args.name, "depth_frac": 0.0, "burn_rate": 0.0,
            "buckets": {"batch": [2, 4], "inner": [4, 8]},
            "served": served["n"],
        }

    def submit(payload, tenant="default", serial=False, timeout_s=30.0):
        served["n"] += 1
        return {"echo": payload, "by": args.name, "serial": serial}

    if args.crash_after is not None:
        # Crash-on-boot: die before the admin surface ever comes up,
        # so readiness can never be (transiently) observed.
        time.sleep(args.crash_after)
        return 17  # unplanned death: no record on stdout

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())

    server = AdminServer(
        snapshot_fn=snapshot, submit_fn=submit, port=args.admin_port,
    ).start()
    stop.wait()
    server.stop()
    record = {
        "app": "fleet-worker-stub", "name": args.name, "role": args.role,
        "served": served["n"],
        "tuner_armed": os.environ.get("DSDDMM_TUNER") == "1",
    }
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
