"""Shardable traces: reroute footgun, offset-aligned merge, round-trip.

Covers the PR-7 multi-process trace story:

* an explicit ``--trace PATH.jsonl`` owned by another LIVE process
  reroutes this process into ``PATH.shards/<run_id>.jsonl`` instead of
  truncating/interleaving (the multi-process footgun fix), and enabling
  with an explicit file exports the shard directory to children via
  ``DSDDMM_TRACE`` (restored on disable);
* ``obs.tracemerge`` merges shards with skewed clock origins into ONE
  monotonic, schema-valid trace (ids disjoint, parents rewritten,
  offsets applied from each begin record's ``t0_epoch`` header);
* histogram merge is associative and commutative (the property that
  makes multi-process latency aggregation meaningful at all);
* the merged file round-trips through ``bench report-trace`` (exit 0)
  and ``bench trace-merge`` (the CLI path).
"""

import json
import os

import pytest

from distributed_sddmm_tpu.obs import trace, tracemerge
from distributed_sddmm_tpu.obs.telemetry import LatencyHistogram
from distributed_sddmm_tpu.tools import tracereport


@pytest.fixture(autouse=True)
def _clean_tracer(monkeypatch):
    monkeypatch.delenv("DSDDMM_TRACE", raising=False)
    trace.disable()
    yield
    trace.disable()


def _shard(path, run_id, t0_epoch, pid, spans=(), events=()):
    """Write one synthetic shard file (schema v1)."""
    recs = [{"type": "begin", "schema": 1, "run_id": run_id,
             "t0_epoch": t0_epoch, "pid": pid}]
    for i, (name, t0, t1) in enumerate(spans, 1):
        recs.append({"type": "span", "name": name, "id": i,
                     "parent": None, "tid": 1, "t0": t0, "t1": t1,
                     "dur_s": round(t1 - t0, 9), "attrs": {}})
    for j, (name, t) in enumerate(events, len(spans) + 1):
        recs.append({"type": "event", "name": name, "id": j,
                     "parent": None, "tid": 1, "t": t, "attrs": {}})
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    return path


class TestShardReroute:
    def test_explicit_file_owned_by_live_process_becomes_shard(
        self, tmp_path
    ):
        stem = tmp_path / "t.jsonl"
        # A live foreign owner: pid 1 (init) always exists.
        stem.write_text(json.dumps({
            "type": "begin", "schema": 1, "run_id": "parent",
            "t0_epoch": 100.0, "pid": 1,
        }) + "\n")
        before = stem.read_text()
        tr = trace.enable(stem)
        assert tr.path.parent == tmp_path / "t.shards"
        assert tr.path.suffix == ".jsonl"
        trace.disable()
        assert stem.read_text() == before  # parent file untouched

    def test_own_or_dead_owner_truncates_as_before(self, tmp_path):
        stem = tmp_path / "t.jsonl"
        stem.write_text(json.dumps({
            "type": "begin", "schema": 1, "run_id": "old",
            "t0_epoch": 1.0, "pid": os.getpid(),
        }) + "\n")
        tr = trace.enable(stem)
        assert tr.path == stem  # same process re-runs in place
        trace.disable()

    def test_enable_exports_shard_dir_and_disable_restores(
        self, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("DSDDMM_TRACE", "inherited-spec")
        trace.disable()  # re-latch with the env var present
        stem = tmp_path / "t.jsonl"
        trace.enable(stem)
        assert os.environ["DSDDMM_TRACE"] == str(tmp_path / "t.shards")
        assert trace.shard_dir() == str(tmp_path / "t.shards")
        trace.disable()
        assert os.environ["DSDDMM_TRACE"] == "inherited-spec"
        assert trace.shard_dir() is None


class TestMerge:
    def test_skewed_origins_merge_monotonic_and_valid(self, tmp_path):
        # Shard B's process started 2.5 wall seconds after shard A's.
        a = _shard(tmp_path / "a.jsonl", "rid-a", 1000.0, 11,
                   spans=[("opA", 0.1, 0.2)], events=[("evA", 0.15)])
        b = _shard(tmp_path / "b.jsonl", "rid-b", 1002.5, 22,
                   spans=[("opB", 0.1, 0.3)], events=[("evB", 0.05)])
        merged = tracemerge.merge([a, b], strict=True)
        assert merged["begin"]["t0_epoch"] == 1000.0
        assert len(merged["begin"]["shards"]) == 2
        sp = {s["name"]: s for s in merged["spans"]}
        # Shard A keeps its times; shard B shifts by +2.5.
        assert sp["opA"]["t0"] == pytest.approx(0.1)
        assert sp["opB"]["t0"] == pytest.approx(2.6)
        assert sp["opB"]["t1"] == pytest.approx(2.8)
        assert sp["opB"]["dur_s"] == pytest.approx(0.2)  # duration kept
        ev = {e["name"]: e for e in merged["events"]}
        assert ev["evB"]["t"] == pytest.approx(2.55)
        # Ids disjoint, every record shard-tagged.
        ids = [r["id"] for r in merged["spans"] + merged["events"]]
        assert len(ids) == len(set(ids))
        assert {r["shard"] for r in merged["spans"]} == {"rid-a", "rid-b"}
        assert sp["opB"]["pid"] == 22

    def test_write_merged_is_schema_valid_and_sorted(self, tmp_path):
        a = _shard(tmp_path / "a.jsonl", "rid-a", 50.0, 11,
                   spans=[("x", 0.0, 1.0), ("y", 1.0, 2.0)])
        b = _shard(tmp_path / "b.jsonl", "rid-b", 50.5, 22,
                   spans=[("z", 0.1, 0.2)])
        out, merged = tracemerge.write_merged([a, b], tmp_path / "m.jsonl")
        loaded = tracereport.load_trace(out, strict=True)
        assert loaded["errors"] == []
        assert len(loaded["spans"]) == 3
        # Time-sorted output.
        t0s = [s["t0"] for s in loaded["spans"]]
        assert t0s == sorted(t0s)

    def test_parent_links_rewritten(self, tmp_path):
        a = tmp_path / "a.jsonl"
        a.write_text("\n".join(json.dumps(r) for r in [
            {"type": "begin", "schema": 1, "run_id": "ra",
             "t0_epoch": 10.0, "pid": 1},
            {"type": "span", "name": "child", "id": 2, "parent": 1,
             "tid": 1, "t0": 0.1, "t1": 0.2, "dur_s": 0.1, "attrs": {}},
            {"type": "span", "name": "root", "id": 1, "parent": None,
             "tid": 1, "t0": 0.0, "t1": 0.3, "dur_s": 0.3, "attrs": {}},
        ]) + "\n")
        b = _shard(tmp_path / "b.jsonl", "rb", 11.0, 2,
                   spans=[("other", 0.0, 0.1)])
        merged = tracemerge.merge([a, b])
        sp = {s["name"]: s for s in merged["spans"]}
        assert sp["child"]["parent"] == sp["root"]["id"]
        assert sp["other"]["id"] not in (sp["root"]["id"], sp["child"]["id"])

    def test_discover_stem_plus_shards_dir(self, tmp_path):
        stem = _shard(tmp_path / "t.jsonl", "parent", 1.0, 1,
                      spans=[("p", 0.0, 0.1)])
        _shard(tmp_path / "t.shards" / "w1.jsonl", "w1", 1.5, 2,
               spans=[("w", 0.0, 0.1)])
        paths = tracemerge.discover(stem)
        assert len(paths) == 2 and paths[0] == stem

    def test_real_tracer_shards_merge(self, tmp_path):
        """Two actual Tracer instances (as two processes would write)
        merge into a valid trace."""
        t1 = trace.Tracer(tmp_path / "p1.jsonl", "p1-rid")
        with trace.Span(t1, "work1", {}):
            pass
        t1.close()
        t2 = trace.Tracer(tmp_path / "p2.jsonl", "p2-rid")
        with trace.Span(t2, "work2", {}):
            pass
        t2.close()
        out, merged = tracemerge.write_merged(
            tracemerge.discover(tmp_path), tmp_path / "m.jsonl"
        )
        loaded = tracereport.load_trace(out, strict=True)
        assert {s["name"] for s in loaded["spans"]} == {"work1", "work2"}

    def test_unmergeable_raises(self, tmp_path):
        bad = tmp_path / "bad.jsonl"
        bad.write_text("not json\n")
        with pytest.raises(ValueError):
            tracemerge.merge([bad], strict=True)
        with pytest.raises(FileNotFoundError):
            tracemerge.discover(tmp_path / "nope.jsonl")


class TestHistogramMergeAlgebra:
    def _h(self, values_ms):
        h = LatencyHistogram()
        for v in values_ms:
            h.add(v)
        return h

    def test_commutative(self):
        a = self._h([0.3, 5, 120, 9000])
        b = self._h([1, 1, 40000])
        assert a.merge(b) == b.merge(a)

    def test_associative(self):
        a, b, c = (self._h([0.1, 2]), self._h([30, 400]),
                   self._h([60000, 0.2]))
        assert a.merge(b).merge(c) == a.merge(b.merge(c))

    def test_merge_preserves_total_and_quantiles(self):
        a = self._h([1.0] * 90)
        b = self._h([200.0] * 10)
        m = a.merge(b)
        assert m.total == 100
        assert m.quantile_ms(50) <= 2.0
        assert m.quantile_ms(99) >= 200.0

    def test_bounds_mismatch_raises(self):
        a = LatencyHistogram()
        b = LatencyHistogram(bounds_ms=(1.0, 10.0))
        with pytest.raises(ValueError):
            a.merge(b)


class TestCLIRoundTrip:
    def test_trace_merge_then_report_trace_exit_0(self, tmp_path):
        from distributed_sddmm_tpu.bench import cli

        _shard(tmp_path / "s" / "a.jsonl", "ra", 5.0, 1,
               spans=[("op", 0.0, 0.1)])
        _shard(tmp_path / "s" / "b.jsonl", "rb", 6.0, 2,
               spans=[("op", 0.0, 0.2)], events=[("e", 0.1)])
        out = tmp_path / "merged.jsonl"
        rc = cli.main(["trace-merge", str(tmp_path / "s"),
                       "-o", str(out)])
        assert rc == 0 and out.exists()
        assert cli.main(["report-trace", str(out)]) == 0

    def test_trace_merge_invalid_exits_2(self, tmp_path):
        from distributed_sddmm_tpu.bench import cli

        bad = tmp_path / "bad.jsonl"
        bad.write_text("garbage\n")
        assert cli.main(["trace-merge", str(bad)]) == 2
