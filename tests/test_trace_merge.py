"""Shardable traces: reroute footgun, offset-aligned merge, round-trip.

Covers the PR-7 multi-process trace story:

* an explicit ``--trace PATH.jsonl`` owned by another LIVE process
  reroutes this process into ``PATH.shards/<run_id>.jsonl`` instead of
  truncating/interleaving (the multi-process footgun fix), and enabling
  with an explicit file exports the shard directory to children via
  ``DSDDMM_TRACE`` (restored on disable);
* ``obs.tracemerge`` merges shards with skewed clock origins into ONE
  monotonic, schema-valid trace (ids disjoint, parents rewritten,
  offsets applied from each begin record's ``t0_epoch`` header);
* histogram merge is associative and commutative (the property that
  makes multi-process latency aggregation meaningful at all);
* the merged file round-trips through ``bench report-trace`` (exit 0)
  and ``bench trace-merge`` (the CLI path).
"""

import json
import os

import pytest

from distributed_sddmm_tpu.obs import trace, tracemerge
from distributed_sddmm_tpu.obs.telemetry import LatencyHistogram
from distributed_sddmm_tpu.tools import tracereport


@pytest.fixture(autouse=True)
def _clean_tracer(monkeypatch):
    monkeypatch.delenv("DSDDMM_TRACE", raising=False)
    trace.disable()
    yield
    trace.disable()


def _shard(path, run_id, t0_epoch, pid, spans=(), events=()):
    """Write one synthetic shard file (schema v1)."""
    recs = [{"type": "begin", "schema": 1, "run_id": run_id,
             "t0_epoch": t0_epoch, "pid": pid}]
    for i, (name, t0, t1) in enumerate(spans, 1):
        recs.append({"type": "span", "name": name, "id": i,
                     "parent": None, "tid": 1, "t0": t0, "t1": t1,
                     "dur_s": round(t1 - t0, 9), "attrs": {}})
    for j, (name, t) in enumerate(events, len(spans) + 1):
        recs.append({"type": "event", "name": name, "id": j,
                     "parent": None, "tid": 1, "t": t, "attrs": {}})
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    return path


class TestShardReroute:
    def test_explicit_file_owned_by_live_process_becomes_shard(
        self, tmp_path
    ):
        stem = tmp_path / "t.jsonl"
        # A live foreign owner: pid 1 (init) always exists.
        stem.write_text(json.dumps({
            "type": "begin", "schema": 1, "run_id": "parent",
            "t0_epoch": 100.0, "pid": 1,
        }) + "\n")
        before = stem.read_text()
        tr = trace.enable(stem)
        assert tr.path.parent == tmp_path / "t.shards"
        assert tr.path.suffix == ".jsonl"
        trace.disable()
        assert stem.read_text() == before  # parent file untouched

    def test_own_or_dead_owner_truncates_as_before(self, tmp_path):
        stem = tmp_path / "t.jsonl"
        stem.write_text(json.dumps({
            "type": "begin", "schema": 1, "run_id": "old",
            "t0_epoch": 1.0, "pid": os.getpid(),
        }) + "\n")
        tr = trace.enable(stem)
        assert tr.path == stem  # same process re-runs in place
        trace.disable()

    def test_enable_exports_shard_dir_and_disable_restores(
        self, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("DSDDMM_TRACE", "inherited-spec")
        trace.disable()  # re-latch with the env var present
        stem = tmp_path / "t.jsonl"
        trace.enable(stem)
        assert os.environ["DSDDMM_TRACE"] == str(tmp_path / "t.shards")
        assert trace.shard_dir() == str(tmp_path / "t.shards")
        trace.disable()
        assert os.environ["DSDDMM_TRACE"] == "inherited-spec"
        assert trace.shard_dir() is None


class TestMerge:
    def test_skewed_origins_merge_monotonic_and_valid(self, tmp_path):
        # Shard B's process started 2.5 wall seconds after shard A's.
        a = _shard(tmp_path / "a.jsonl", "rid-a", 1000.0, 11,
                   spans=[("opA", 0.1, 0.2)], events=[("evA", 0.15)])
        b = _shard(tmp_path / "b.jsonl", "rid-b", 1002.5, 22,
                   spans=[("opB", 0.1, 0.3)], events=[("evB", 0.05)])
        merged = tracemerge.merge([a, b], strict=True)
        assert merged["begin"]["t0_epoch"] == 1000.0
        assert len(merged["begin"]["shards"]) == 2
        sp = {s["name"]: s for s in merged["spans"]}
        # Shard A keeps its times; shard B shifts by +2.5.
        assert sp["opA"]["t0"] == pytest.approx(0.1)
        assert sp["opB"]["t0"] == pytest.approx(2.6)
        assert sp["opB"]["t1"] == pytest.approx(2.8)
        assert sp["opB"]["dur_s"] == pytest.approx(0.2)  # duration kept
        ev = {e["name"]: e for e in merged["events"]}
        assert ev["evB"]["t"] == pytest.approx(2.55)
        # Ids disjoint, every record shard-tagged.
        ids = [r["id"] for r in merged["spans"] + merged["events"]]
        assert len(ids) == len(set(ids))
        assert {r["shard"] for r in merged["spans"]} == {"rid-a", "rid-b"}
        assert sp["opB"]["pid"] == 22

    def test_write_merged_is_schema_valid_and_sorted(self, tmp_path):
        a = _shard(tmp_path / "a.jsonl", "rid-a", 50.0, 11,
                   spans=[("x", 0.0, 1.0), ("y", 1.0, 2.0)])
        b = _shard(tmp_path / "b.jsonl", "rid-b", 50.5, 22,
                   spans=[("z", 0.1, 0.2)])
        out, merged = tracemerge.write_merged([a, b], tmp_path / "m.jsonl")
        loaded = tracereport.load_trace(out, strict=True)
        assert loaded["errors"] == []
        assert len(loaded["spans"]) == 3
        # Time-sorted output.
        t0s = [s["t0"] for s in loaded["spans"]]
        assert t0s == sorted(t0s)

    def test_parent_links_rewritten(self, tmp_path):
        a = tmp_path / "a.jsonl"
        a.write_text("\n".join(json.dumps(r) for r in [
            {"type": "begin", "schema": 1, "run_id": "ra",
             "t0_epoch": 10.0, "pid": 1},
            {"type": "span", "name": "child", "id": 2, "parent": 1,
             "tid": 1, "t0": 0.1, "t1": 0.2, "dur_s": 0.1, "attrs": {}},
            {"type": "span", "name": "root", "id": 1, "parent": None,
             "tid": 1, "t0": 0.0, "t1": 0.3, "dur_s": 0.3, "attrs": {}},
        ]) + "\n")
        b = _shard(tmp_path / "b.jsonl", "rb", 11.0, 2,
                   spans=[("other", 0.0, 0.1)])
        merged = tracemerge.merge([a, b])
        sp = {s["name"]: s for s in merged["spans"]}
        assert sp["child"]["parent"] == sp["root"]["id"]
        assert sp["other"]["id"] not in (sp["root"]["id"], sp["child"]["id"])

    def test_discover_stem_plus_shards_dir(self, tmp_path):
        stem = _shard(tmp_path / "t.jsonl", "parent", 1.0, 1,
                      spans=[("p", 0.0, 0.1)])
        _shard(tmp_path / "t.shards" / "w1.jsonl", "w1", 1.5, 2,
               spans=[("w", 0.0, 0.1)])
        paths = tracemerge.discover(stem)
        assert len(paths) == 2 and paths[0] == stem

    def test_real_tracer_shards_merge(self, tmp_path):
        """Two actual Tracer instances (as two processes would write)
        merge into a valid trace."""
        t1 = trace.Tracer(tmp_path / "p1.jsonl", "p1-rid")
        with trace.Span(t1, "work1", {}):
            pass
        t1.close()
        t2 = trace.Tracer(tmp_path / "p2.jsonl", "p2-rid")
        with trace.Span(t2, "work2", {}):
            pass
        t2.close()
        out, merged = tracemerge.write_merged(
            tracemerge.discover(tmp_path), tmp_path / "m.jsonl"
        )
        loaded = tracereport.load_trace(out, strict=True)
        assert {s["name"] for s in loaded["spans"]} == {"work1", "work2"}

    def test_unmergeable_raises(self, tmp_path):
        bad = tmp_path / "bad.jsonl"
        bad.write_text("not json\n")
        with pytest.raises(ValueError):
            tracemerge.merge([bad], strict=True)
        with pytest.raises(FileNotFoundError):
            tracemerge.discover(tmp_path / "nope.jsonl")


class TestHistogramMergeAlgebra:
    def _h(self, values_ms):
        h = LatencyHistogram()
        for v in values_ms:
            h.add(v)
        return h

    def test_commutative(self):
        a = self._h([0.3, 5, 120, 9000])
        b = self._h([1, 1, 40000])
        assert a.merge(b) == b.merge(a)

    def test_associative(self):
        a, b, c = (self._h([0.1, 2]), self._h([30, 400]),
                   self._h([60000, 0.2]))
        assert a.merge(b).merge(c) == a.merge(b.merge(c))

    def test_merge_preserves_total_and_quantiles(self):
        a = self._h([1.0] * 90)
        b = self._h([200.0] * 10)
        m = a.merge(b)
        assert m.total == 100
        assert m.quantile_ms(50) <= 2.0
        assert m.quantile_ms(99) >= 200.0

    def test_bounds_mismatch_raises(self):
        a = LatencyHistogram()
        b = LatencyHistogram(bounds_ms=(1.0, 10.0))
        with pytest.raises(ValueError):
            a.merge(b)


class TestCLIRoundTrip:
    def test_trace_merge_then_report_trace_exit_0(self, tmp_path):
        from distributed_sddmm_tpu.bench import cli

        _shard(tmp_path / "s" / "a.jsonl", "ra", 5.0, 1,
               spans=[("op", 0.0, 0.1)])
        _shard(tmp_path / "s" / "b.jsonl", "rb", 6.0, 2,
               spans=[("op", 0.0, 0.2)], events=[("e", 0.1)])
        out = tmp_path / "merged.jsonl"
        rc = cli.main(["trace-merge", str(tmp_path / "s"),
                       "-o", str(out)])
        assert rc == 0 and out.exists()
        assert cli.main(["report-trace", str(out)]) == 0

    def test_trace_merge_invalid_exits_2(self, tmp_path):
        from distributed_sddmm_tpu.bench import cli

        bad = tmp_path / "bad.jsonl"
        bad.write_text("garbage\n")
        assert cli.main(["trace-merge", str(bad)]) == 2


# --------------------------------------------------------------------- #
# Fleet tracing (PR 19): wire context, cross-shard links, chains
# --------------------------------------------------------------------- #


def _raw_shard(path, run_id, t0_epoch, pid, records):
    recs = [{"type": "begin", "schema": 1, "run_id": run_id,
             "t0_epoch": t0_epoch, "pid": pid}] + list(records)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    return path


def _router_shard(path, lat_s=0.2998, outcome="ok"):
    """Router-side shard: a fleet:request span, its caller-thread
    primary attempt, and a parentless side-thread hedge attempt that
    names its request via ``fleet_span`` (own shard)."""
    return _raw_shard(path, "rt", 1000.0, 11, [
        {"type": "span", "name": "fleet:request", "id": 1, "parent": None,
         "tid": 1, "t0": 0.0, "t1": 0.5, "dur_s": 0.5,
         "attrs": {"fleet_req": "fr-1", "tenant": "default",
                   "outcome": outcome, "winner": "r0", "serial": False}},
        {"type": "span", "name": "fleet:attempt", "id": 2, "parent": 1,
         "tid": 1, "t0": 0.01, "t1": 0.31, "dur_s": 0.3,
         "attrs": {"fleet_req": "fr-1", "replica": "r0",
                   "kind": "primary", "ordinal": 0, "outcome": "ok",
                   "lat_s": lat_s}},
        {"type": "span", "name": "fleet:attempt", "id": 3, "parent": None,
         "tid": 2, "t0": 0.05, "t1": 0.25, "dur_s": 0.2,
         "attrs": {"fleet_req": "fr-1", "fleet_span": 1,
                   "replica": "r1", "kind": "hedge", "ordinal": 0,
                   "outcome": "hedge_loser"}},
    ])


def _replica_shard(path):
    """Replica-side shard (clock origin 2.5s later): the enqueue and
    reply events carry the fleet context decoded off the submit
    header — ``fleet_shard``/``fleet_span`` name the router attempt."""
    return _raw_shard(path, "rp", 1002.5, 22, [
        {"type": "event", "name": "serve:enqueue", "id": 1,
         "parent": None, "tid": 1, "t": 0.02,
         "attrs": {"req": "q7", "tenant": "default", "fleet_req": "fr-1",
                   "fleet_shard": "rt", "fleet_span": 2}},
        {"type": "span", "name": "serve:batch", "id": 2, "parent": None,
         "tid": 1, "t0": 0.05, "t1": 0.25, "dur_s": 0.2,
         "attrs": {"req_ids": ["q7"]}},
        {"type": "event", "name": "serve:reply", "id": 3, "parent": 2,
         "tid": 1, "t": 0.25,
         "attrs": {"req": "q7", "fleet_req": "fr-1", "fleet_shard": "rt",
                   "fleet_span": 2, "t_enqueue": 0.02, "t_reply": 0.25,
                   "queue_s": 0.03, "batch_wait_s": 0.0,
                   "execute_s": 0.2, "total_s": 0.23}},
    ])


class TestFleetCtxHeader:
    def test_roundtrip(self):
        ctx = {"req": "fr-9", "shard": "rt", "span": 17, "kind": "hedge",
               "ord": 2}
        assert trace.decode_fleet_ctx(trace.encode_fleet_ctx(ctx)) == ctx

    def test_none_fields_omitted(self):
        hdr = trace.encode_fleet_ctx({"req": "fr-1", "span": None})
        assert "span" not in hdr
        assert trace.decode_fleet_ctx(hdr) == {"req": "fr-1"}

    def test_garbage_and_missing_req_decode_to_none(self):
        assert trace.decode_fleet_ctx(None) is None
        assert trace.decode_fleet_ctx("") is None
        assert trace.decode_fleet_ctx("zzz") is None
        assert trace.decode_fleet_ctx("v2;req=x") is None  # unknown ver
        assert trace.decode_fleet_ctx("v1;shard=rt") is None  # no req

    def test_bad_int_field_dropped_not_fatal(self):
        got = trace.decode_fleet_ctx("v1;req=fr-1;span=abc;ord=3")
        assert got == {"req": "fr-1", "ord": 3}


class TestFleetLinks:
    def test_cross_shard_enqueue_reparented_onto_attempt(self, tmp_path):
        merged = tracemerge.merge([
            _router_shard(tmp_path / "rt.jsonl"),
            _replica_shard(tmp_path / "rp.jsonl"),
        ])
        sp = {s["name"]: s for s in merged["spans"]
              if s["name"] != "fleet:attempt"}
        att = {s["attrs"]["kind"]: s for s in merged["spans"]
               if s["name"] == "fleet:attempt"}
        ev = {e["name"]: e for e in merged["events"]}
        # The replica's enqueue (no in-process parent) re-parents onto
        # the router's attempt span across shards.
        assert ev["serve:enqueue"]["parent"] == att["primary"]["id"]
        assert (ev["serve:enqueue"]["attrs"]["fleet_parent"]
                == att["primary"]["id"])
        # serve:reply keeps its in-process nesting under serve:batch —
        # the link is recorded as an attr only.
        assert ev["serve:reply"]["parent"] == sp["serve:batch"]["id"]
        assert (ev["serve:reply"]["attrs"]["fleet_parent"]
                == att["primary"]["id"])
        # The side-thread hedge attempt re-parents onto its request
        # span within its OWN shard (no fleet_shard attr).
        assert att["hedge"]["parent"] == sp["fleet:request"]["id"]
        assert merged["begin"]["fleet_links"] == 3

    def test_skewed_origins_ids_disjoint_and_links_precise(self, tmp_path):
        # Both shards use original span id 1 — the per-shard spanmap
        # must resolve the hedge's fleet_span=1 to the ROUTER's request
        # span, never the replica's record that reused the id.
        merged = tracemerge.merge([
            _router_shard(tmp_path / "rt.jsonl"),
            _replica_shard(tmp_path / "rp.jsonl"),
        ])
        ids = [r["id"] for r in merged["spans"] + merged["events"]]
        assert len(ids) == len(set(ids))
        req = next(s for s in merged["spans"]
                   if s["name"] == "fleet:request")
        hedge = next(s for s in merged["spans"]
                     if s["attrs"].get("kind") == "hedge")
        assert hedge["parent"] == req["id"] and req["shard"] == "rt"
        # The replica's records shifted by the +2.5s origin skew.
        enq = next(e for e in merged["events"]
                   if e["name"] == "serve:enqueue")
        assert enq["t"] == pytest.approx(2.52)
        # write_merged revalidates: the rewrite produced a valid trace.
        out, _ = tracemerge.write_merged(
            [tmp_path / "rt.jsonl", tmp_path / "rp.jsonl"],
            tmp_path / "m.jsonl",
        )
        assert tracereport.load_trace(out, strict=True)["errors"] == []

    def test_unresolvable_fleet_link_left_alone(self, tmp_path):
        a = _raw_shard(tmp_path / "a.jsonl", "ra", 1.0, 1, [
            {"type": "event", "name": "serve:enqueue", "id": 1,
             "parent": None, "tid": 1, "t": 0.1,
             "attrs": {"req": "q1", "fleet_req": "fr-1",
                       "fleet_shard": "nope", "fleet_span": 99}},
        ])
        merged = tracemerge.merge([a])
        ev = merged["events"][0]
        assert ev["parent"] is None
        assert "fleet_parent" not in ev["attrs"]
        assert merged["begin"]["fleet_links"] == 0


class TestFleetChains:
    def _merged(self, tmp_path, **router_kw):
        return tracemerge.merge([
            _router_shard(tmp_path / "rt.jsonl", **router_kw),
            _replica_shard(tmp_path / "rp.jsonl"),
        ])

    def test_complete_chain_full_coverage(self, tmp_path):
        chains = tracereport.fleet_request_chains(self._merged(tmp_path))
        assert chains["delivered"] == 1 and chains["complete"] == 1
        assert chains["coverage"] == 1.0 and chains["hedged"] == 1
        ch = chains["requests"]["fr-1"]
        assert ch["complete"] and ch["winner"] == "r0"
        kinds = [r["kind"] for r in ch["attempts"]]
        assert kinds == ["primary", "hedge"]
        # Segment attribution: router overhead + wire + the replica's
        # own queue/batch/execute partition.
        assert ch["segments"]["router_s"] == pytest.approx(0.2)
        assert ch["segments"]["wire_s"] == pytest.approx(0.0698)
        assert ch["replica_chain"]["segments"]["execute_s"] == 0.2

    def test_lat_disagreement_breaks_coverage(self, tmp_path):
        # Router recorded 200ms but the span measured 300ms: the >1ms
        # disagreement means the trace no longer explains the latency
        # the router acted on — the chain must NOT count as complete.
        chains = tracereport.fleet_request_chains(
            self._merged(tmp_path, lat_s=0.2)
        )
        assert chains["delivered"] == 1 and chains["complete"] == 0
        assert chains["coverage"] == 0.0

    def test_failed_request_is_annotated_not_counted(self, tmp_path):
        chains = tracereport.fleet_request_chains(
            self._merged(tmp_path, outcome="error")
        )
        assert chains["delivered"] == 0 and chains["failed"] == 1
        assert chains["coverage"] == 1.0  # nothing delivered = clean

    def test_serial_tier_needs_no_replica_chain(self, tmp_path):
        a = _raw_shard(tmp_path / "rt.jsonl", "rt", 1.0, 1, [
            {"type": "span", "name": "fleet:request", "id": 1,
             "parent": None, "tid": 1, "t0": 0.0, "t1": 0.4, "dur_s": 0.4,
             "attrs": {"fleet_req": "fr-2", "tenant": "default",
                       "outcome": "ok", "winner": "r0", "serial": True}},
            {"type": "span", "name": "fleet:attempt", "id": 2,
             "parent": 1, "tid": 1, "t0": 0.01, "t1": 0.31, "dur_s": 0.3,
             "attrs": {"fleet_req": "fr-2", "replica": "r0",
                       "kind": "primary", "ordinal": 0, "outcome": "ok",
                       "lat_s": 0.2999}},
        ])
        chains = tracereport.fleet_request_chains(tracemerge.merge([a]))
        assert chains["coverage"] == 1.0
        assert chains["requests"]["fr-2"]["complete"]

    def test_aggregate_and_render_carry_fleet_block(self, tmp_path):
        out, _ = tracemerge.write_merged(
            [_router_shard(tmp_path / "rt.jsonl"),
             _replica_shard(tmp_path / "rp.jsonl")],
            tmp_path / "m.jsonl",
        )
        trace_doc = tracereport.load_trace(out, strict=True)
        report = tracereport.aggregate(trace_doc)
        fl = report["fleet"]
        assert fl["coverage"] == 1.0 and fl["delivered"] == 1
        assert "router_s" in fl["mean_segments_ms"]
        assert "fleet" in tracereport.render(report)
