"""Fault matrix: every strategy degrades — never hangs, never lies.

For each of the five algorithm configurations and each synthetic fault
family (NaN corruption, timeout, OOM), a *transient* fault (fires exactly
once) must heal through the retry path — the final result fingerprint
equals the un-faulted run's — and a *persistent* fault must surface as a
clean typed exception after bounded attempts. The worker-kill row runs a
real OS-level preemption of a pod worker and asserts the parent detects
it without hanging.

Determinism note: plans fire by (seed, spec, site, call-count), so each
test constructs a fresh strategy — program/call counters must start from
zero for "fires at call 0" to mean the first dispatch.
"""

import os
import pathlib
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from distributed_sddmm_tpu.common import MatMode
from distributed_sddmm_tpu.parallel.cannon_dense_25d import CannonDense25D
from distributed_sddmm_tpu.parallel.cannon_sparse_25d import CannonSparse25D
from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
from distributed_sddmm_tpu.parallel.sparse_shift_15d import SparseShift15D
from distributed_sddmm_tpu.resilience import (
    FaultError, FaultPlan, FaultSpec, fault_plan, faults,
)
from distributed_sddmm_tpu.utils.coo import HostCOO

ROOT = pathlib.Path(__file__).resolve().parents[1]

STRATEGIES = [
    ("15d_fusion1", lambda S: DenseShift15D(S, R=8, c=2, fusion_approach=1)),
    ("15d_fusion2", lambda S: DenseShift15D(S, R=8, c=2, fusion_approach=2)),
    ("15d_sparse", lambda S: SparseShift15D(S, R=8, c=2)),
    ("25d_dense", lambda S: CannonDense25D(S, R=8, c=2)),
    ("25d_sparse", lambda S: CannonSparse25D(S, R=8, c=2)),
]

TRANSIENT_FAULTS = [
    ("nan", FaultSpec(site="output:*", kind="nan", at=(0,), param=0.2)),
    ("inf", FaultSpec(site="output:*", kind="inf", at=(0,), param=0.2)),
    ("timeout", FaultSpec(site="execute:*", kind="timeout", at=(0,))),
    ("oom", FaultSpec(site="execute:*", kind="oom", at=(0,))),
]


def _problem():
    return HostCOO.erdos_renyi(48, 32, 5, seed=0)


def _fused_fingerprint(alg):
    A = alg.dummy_initialize(MatMode.A)
    B = alg.dummy_initialize(MatMode.B)
    out, mid = alg.fused_spmm(A, B, alg.like_s_values(1.0), MatMode.A)
    return alg.fingerprint(out), alg.fingerprint(mid)


# The retry/guard ladder lives ONCE at parallel/base._resilient_call;
# the full 5x4 (strategy x kind) product is defensive overlap. Kept
# strict: every kind on 15d_fusion2 (the headline strategy), plus one
# execute-site kind (timeout) and one output-site kind (nan) on every
# other strategy — the two hook families each strategy's output pytree
# actually shapes. The remaining cells are slow-marked (PR 14 budget
# satellite), not deleted.
_HEAL_MATRIX = [
    pytest.param(
        sname, mk, fname, spec, id=f"{sname}-{fname}",
        marks=() if (sname == "15d_fusion2" or fname in ("nan", "timeout"))
        else (pytest.mark.slow,),
    )
    for sname, mk in STRATEGIES for fname, spec in TRANSIENT_FAULTS
]


@pytest.mark.parametrize("sname,mk,fname,spec", _HEAL_MATRIX)
def test_transient_fault_heals_to_identical_result(sname, mk, fname, spec):
    """One injected fault on the first dispatch; the retry path must
    produce a result identical to a clean run — healed, not approximated."""
    S = _problem()
    want = _fused_fingerprint(mk(S))

    plan = FaultPlan([spec])
    with fault_plan(plan):
        got = _fused_fingerprint(mk(S))
    assert plan.events, "the fault never fired — the matrix row is vacuous"
    assert got == want


@pytest.mark.parametrize("sname,mk", STRATEGIES, ids=[s[0] for s in STRATEGIES])
def test_persistent_fault_raises_cleanly(sname, mk):
    """Every dispatch times out: after bounded retries the op must raise
    the typed injected error — quickly, not after minutes of backoff."""
    S = _problem()
    plan = FaultPlan([FaultSpec(site="execute:*", kind="timeout", prob=1.0)])
    t0 = time.monotonic()
    with fault_plan(plan):
        alg = mk(S)
        with pytest.raises(TimeoutError):
            _fused_fingerprint(alg)
    assert time.monotonic() - t0 < 60.0


def test_persistent_nan_raises_numerical_fault():
    """Persistent corruption with guards on must surface NumericalFault,
    never return a poisoned array as if it were the answer."""
    from distributed_sddmm_tpu.resilience.guards import NumericalFault

    S = _problem()
    plan = FaultPlan([FaultSpec(site="output:*", kind="nan", prob=1.0, param=0.1)])
    with fault_plan(plan):
        alg = DenseShift15D(S, R=8, c=2, fusion_approach=2)
        with pytest.raises(NumericalFault):
            _fused_fingerprint(alg)


def test_repair_mode_degrades_instead_of_raising(monkeypatch):
    """DSDDMM_GUARD_MODE=repair turns a persistently poisoned output into
    a nan_to_num-damped one — finite, flagged on stderr, run continues."""
    monkeypatch.setenv("DSDDMM_GUARD_MODE", "repair")
    S = _problem()
    plan = FaultPlan([FaultSpec(site="output:*", kind="nan", prob=1.0, param=0.1)])
    with fault_plan(plan):
        alg = DenseShift15D(S, R=8, c=2, fusion_approach=2)
        fp_out, fp_mid = _fused_fingerprint(alg)
    assert np.isfinite(fp_out) and np.isfinite(fp_mid)


def test_fault_plan_is_deterministic():
    """Same seed + same call sequence = identical firing pattern (the
    property that lets the matrix assert exact recovery behavior)."""
    def run(seed):
        plan = FaultPlan(
            [FaultSpec(site="execute:op", kind="timeout", prob=0.3)], seed=seed
        )
        fired = []
        with fault_plan(plan):
            for i in range(32):
                try:
                    faults.maybe_raise("execute:op")
                except TimeoutError:
                    fired.append(i)
        return fired

    a, b = run(seed=3), run(seed=3)
    assert a == b and a  # deterministic AND non-empty at prob=0.3 over 32
    assert run(seed=4) != a  # the seed actually varies the pattern


def test_env_activation_reaches_hooks(monkeypatch):
    """DSDDMM_FAULTS activates lazily — the path subprocess workers use."""
    monkeypatch.setenv(
        "DSDDMM_FAULTS",
        '[{"site": "execute:envcheck", "kind": "error", "at": [0]}]',
    )
    # Reset the module's env-checked latch (tests share the process).
    faults.install(None)
    faults._env_checked = False
    try:
        with pytest.raises(FaultError):
            faults.maybe_raise("execute:envcheck")
    finally:
        faults.install(None)


# One shared bind-port-0 helper for the whole pod surface (PR 14).
from distributed_sddmm_tpu.dist.elastic import free_port as _free_port


def test_worker_kill_detected_without_hang():
    """OS-level preemption: worker 1 of a 2-process pod is killed by its
    fault plan before joining the coordinator. The supervisor (this test)
    must observe the distinctive kill exit code promptly and tear the
    surviving worker down — bounded wall-clock, no indefinite join."""
    port = _free_port()
    env = {k: v for k, v in os.environ.items() if k != "DSDDMM_FAULTS"}
    env["DSDDMM_MP_INIT_TIMEOUT"] = "60"
    kill_env = dict(env)
    kill_env["DSDDMM_FAULTS"] = (
        '[{"site": "mp_worker:start", "kind": "kill", "at": [0]}]'
    )
    procs = [
        subprocess.Popen(
            [sys.executable, str(ROOT / "tests" / "_mp_worker.py"),
             str(pid), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=str(ROOT), env=(kill_env if pid == 1 else env),
        )
        for pid in range(2)
    ]
    try:
        rc = procs[1].wait(timeout=120)
        assert rc == faults.KILL_EXIT_CODE, (rc, procs[1].stderr.read()[-500:])
        # Supervisor response: the peer is gone, tear down the survivor
        # instead of letting it wait out its join.
        procs[0].send_signal(signal.SIGTERM)
        procs[0].wait(timeout=60)
        assert procs[0].returncode != 0  # it had not finished — and said so
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
