"""Partitioned HostCOO loader: bit-identity, memory bound, edge cases.

The pod-scale ingest contract (``dist/ingest.py``): no host ever
materializes the full matrix, and the partitioned parse must be
*indistinguishable* from the whole-matrix loader — assembled shards
bit-match ``HostCOO.load_mtx`` + ``sanitize_coo`` in both strict and
repair modes, at any p, even p ∤ rows, even empty shards. The peak-byte
accounting each shard reports is pinned against the
``O(nnz/p) + O(threads × chunk)`` bound the module documents.
"""

import numpy as np
import pytest

from distributed_sddmm_tpu import native
from distributed_sddmm_tpu.dist import ingest
from distributed_sddmm_tpu.utils.coo import HostCOO, sanitize_coo


def _canon(coo: HostCOO):
    s = coo.sorted_by_row()
    return s.rows, s.cols, s.vals


def _assert_bit_identical(a: HostCOO, b: HostCOO):
    ra, ca, va = _canon(a)
    rb, cb, vb = _canon(b)
    assert a.M == b.M and a.N == b.N
    np.testing.assert_array_equal(ra, rb)
    np.testing.assert_array_equal(ca, cb)
    # Bit identity, not closeness: the streamed parse must produce the
    # exact float64s the whole parse does.
    np.testing.assert_array_equal(va, vb)


@pytest.fixture(scope="module")
def mtx_file(tmp_path_factory):
    rng = np.random.default_rng(7)
    M, N, nnz = 101, 77, 6000  # duplicates guaranteed
    S = HostCOO(rng.integers(0, M, nnz), rng.integers(0, N, nnz),
                rng.standard_normal(nnz), M, N)
    path = tmp_path_factory.mktemp("mtx") / "mat.mtx"
    S.save_mtx(str(path))
    return path, M, N


class TestBitIdenticalAssembly:
    @pytest.mark.parametrize("nproc", [1, 3, 4, 7])
    def test_repair_assembly_matches_whole_loader(self, mtx_file, nproc):
        path, M, N = mtx_file
        whole, _ = sanitize_coo(*native.mtx_read(str(path)), mode="repair")
        shards = [
            ingest.load_mtx_partitioned(
                path, nproc, k, mode="repair", chunk_bytes=2048, threads=3
            )
            for k in range(nproc)
        ]
        # Uneven split (nproc ∤ 101 for 3, 4, 7): ranges still tile
        # [0, M) exactly.
        edges = [s.row0 for s in shards] + [shards[-1].row1]
        assert edges[0] == 0 and edges[-1] == M
        assert all(e1 >= e0 for e0, e1 in zip(edges, edges[1:]))
        _assert_bit_identical(ingest.assemble(shards), whole)
        # Per-shard drop accounting sums to the whole loader's.
        assert sum(s.report["dropped"] for s in shards) == (
            sum(np.bincount([0]) * 0)  # readability anchor: 0 baseline
            + (6000 - whole.nnz)
        )

    def test_strict_on_clean_file_matches(self, tmp_path):
        S = HostCOO.erdos_renyi(64, 50, 3, seed=1, values="normal")
        path = tmp_path / "clean.mtx"
        S.save_mtx(str(path))
        whole, rep = sanitize_coo(*native.mtx_read(str(path)), mode="strict")
        assert rep["duplicates"] == 0
        shards = [
            ingest.load_mtx_partitioned(path, 3, k, mode="strict",
                                        chunk_bytes=1024)
            for k in range(3)
        ]
        _assert_bit_identical(ingest.assemble(shards), whole)

    def test_strict_raises_on_duplicates_like_whole_loader(self, mtx_file):
        path, _M, _N = mtx_file
        with pytest.raises(ValueError, match="duplicates"):
            sanitize_coo(*native.mtx_read(str(path)), mode="strict")
        with pytest.raises(ValueError, match="duplicates"):
            for k in range(3):
                ingest.load_mtx_partitioned(path, 3, k, mode="strict")

    def test_symmetric_expansion_partitions(self, tmp_path):
        scipy_io = pytest.importorskip("scipy.io")
        import scipy.sparse as sp

        A = sp.random(60, 60, density=0.05, random_state=1)
        A = A + A.T
        path = tmp_path / "sym.mtx"
        scipy_io.mmwrite(str(path), A.tocoo(), symmetry="symmetric")
        whole, _ = sanitize_coo(*native.mtx_read(str(path)), mode="repair")
        shards = [
            ingest.load_mtx_partitioned(path, 3, k, mode="repair",
                                        chunk_bytes=1024)
            for k in range(3)
        ]
        _assert_bit_identical(ingest.assemble(shards), whole)


class TestEdgeCases:
    def test_empty_host_shard(self, tmp_path):
        S = HostCOO([0, 2], [1, 0], [1.0, 2.0], 3, 4)
        path = tmp_path / "tiny.mtx"
        S.save_mtx(str(path))
        shards = [ingest.load_mtx_partitioned(path, 5, k) for k in range(5)]
        assert [s.nnz for s in shards] == [1, 0, 1, 0, 0]
        # Hosts beyond the row count own empty, zero-width ranges.
        assert shards[3].row0 == shards[3].row1 == 3
        _assert_bit_identical(ingest.assemble(shards), S)

    def test_row_range_partitions_exactly(self):
        for M in (0, 1, 7, 101, 4096):
            for p in (1, 2, 3, 5, 8):
                ranges = [ingest.row_range(M, p, k) for k in range(p)]
                assert ranges[0][0] == 0 and ranges[-1][1] == M
                for (a0, a1), (b0, b1) in zip(ranges, ranges[1:]):
                    assert a1 == b0
                sizes = [r1 - r0 for r0, r1 in ranges]
                assert max(sizes) - min(sizes) <= 1
        with pytest.raises(ValueError):
            ingest.row_range(10, 2, 2)
        with pytest.raises(ValueError):
            ingest.row_range(10, 0, 0)

    def test_out_of_range_rows_claimed_once_by_shard_zero(self, tmp_path):
        # Hand-write a file whose declared M is smaller than one row
        # index (a truncated-header corruption): the oob row belongs to
        # no shard and must be counted exactly once, by shard 0.
        path = tmp_path / "oob.mtx"
        path.write_text(
            "%%MatrixMarket matrix coordinate real general\n"
            "4 4 3\n"
            "1 1 1.0\n"
            "9 2 5.0\n"   # row 9 > M=4
            "4 4 2.0\n"
        )
        whole, wrep = sanitize_coo(*native.mtx_read(str(path)),
                                   mode="repair")
        shards = [
            ingest.load_mtx_partitioned(path, 2, k, mode="repair")
            for k in range(2)
        ]
        assert wrep["out_of_range"] == 1
        assert shards[0].report["out_of_range"] == 1
        assert shards[1].report["out_of_range"] == 0
        _assert_bit_identical(ingest.assemble(shards), whole)
        # strict: EVERY shard raises (each host scans every line), like
        # the whole loader on every host — one raising worker with the
        # rest proceeding into a collective would be a pod hang.
        for k in range(2):
            with pytest.raises(ValueError, match="out_of_range"):
                ingest.load_mtx_partitioned(path, 2, k, mode="strict")

    def test_truncated_file_fails_loudly_in_every_mode(self, tmp_path):
        """The whole loader raises 'expected N entries, parsed M' on a
        truncated file; the partitioned reader must too — in repair
        mode as well, a short file is corruption, not data."""
        S = HostCOO.erdos_renyi(50, 40, 4, seed=9, values="normal")
        path = tmp_path / "full.mtx"
        S.save_mtx(str(path))
        lines = path.read_text().splitlines()
        cut = tmp_path / "cut.mtx"
        cut.write_text("\n".join(lines[:-7]) + "\n")  # drop 7 entries
        with pytest.raises(IOError, match="parsed"):
            native.mtx_read(str(cut))
        for mode in ("strict", "repair"):
            for k in range(2):
                with pytest.raises(IOError, match="truncated or corrupt"):
                    ingest.load_mtx_partitioned(cut, 2, k, mode=mode)

    def test_interior_comment_lines_skip_like_whole_loader(self, tmp_path):
        path = tmp_path / "comments.mtx"
        path.write_text(
            "%%MatrixMarket matrix coordinate real general\n"
            "4 4 2\n"
            "1 1 1.5\n"
            "% a mid-data comment some writers emit\n"
            "3 4 -2.0\n"
        )
        whole, _ = sanitize_coo(*native.mtx_read(str(path)), mode="strict")
        assert whole.nnz == 2
        shards = [ingest.load_mtx_partitioned(path, 2, k) for k in range(2)]
        _assert_bit_identical(ingest.assemble(shards), whole)

    def test_fractional_index_rejected_on_both_parser_paths(self, tmp_path):
        # '1 2.5 3.0' must not truncate-parse as col 2 / val 0.5 on
        # either path; the whole loader skips it and then fails its
        # declared-count check.
        path = tmp_path / "frac.mtx"
        path.write_text(
            "%%MatrixMarket matrix coordinate real general\n"
            "4 4 2\n"
            "1 1 1.0\n"
            "1 2.5 3.0\n"
        )
        with pytest.raises(IOError):
            native.mtx_read(str(path))
        import os

        for force_fallback in (False, True):
            if force_fallback:
                os.environ["HNH_NO_NATIVE"] = "1"
                native._lib = None
                native._tried = False
            try:
                with pytest.raises((ValueError, IOError)):
                    ingest.load_mtx_partitioned(path, 1, 0, mode="repair")
            finally:
                if force_fallback:
                    os.environ.pop("HNH_NO_NATIVE")
                    native._lib = None
                    native._tried = False

    def test_malformed_line_raises_on_both_parser_paths(self, tmp_path):
        path = tmp_path / "bad.mtx"
        path.write_text(
            "%%MatrixMarket matrix coordinate real general\n"
            "4 4 2\n"
            "1 1 1.5\n"
            "2 2 3.5xx\n"  # non-numeric residue
        )
        with pytest.raises(ValueError):
            ingest.load_mtx_partitioned(path, 1, 0, mode="repair")
        if native.available():
            with pytest.raises(ValueError, match="malformed"):
                native.parse_triplets(b"1 1 1.0\n2 2 3.5xx\n")
            # Blank lines and extra NUMERIC fields stay legal (the
            # numpy fallback skips/slices them).
            r, c, v = native.parse_triplets(b"1 1 1.0\n\n2 2 2.0 9.0\n")
            np.testing.assert_array_equal(r, [0, 1])

    def test_append_rows_on_partitioned_shard(self, tmp_path):
        S = HostCOO.erdos_renyi(40, 30, 3, seed=4, values="normal")
        path = tmp_path / "grow.mtx"
        S.save_mtx(str(path))
        whole, _ = sanitize_coo(*native.mtx_read(str(path)), mode="strict")
        shards = [
            ingest.load_mtx_partitioned(path, 3, k, mode="strict")
            for k in range(3)
        ]
        new_cols = [[1, 5], [2]]
        new_vals = [[0.5, -1.5], [2.25]]
        first_whole, _ = whole.append_rows(new_cols, new_vals)
        # Fold-in lands on the growth edge — the LAST shard's range.
        first_shard, rep = shards[2].append_rows(new_cols, new_vals)
        assert first_shard == first_whole == 40
        assert rep["dropped"] == 0
        assert shards[2].row1 == shards[2].M == 42
        _assert_bit_identical(ingest.assemble(shards), whole)
        with pytest.raises(ValueError, match="last row shard"):
            shards[0].append_rows(new_cols, new_vals)


class TestMemoryBound:
    def test_peak_bytes_scale_with_one_over_p(self, tmp_path):
        rng = np.random.default_rng(3)
        M, N, nnz = 400, 300, 40_000
        S = HostCOO(rng.integers(0, M, nnz), rng.integers(0, N, nnz),
                    rng.standard_normal(nnz), M, N)
        path = tmp_path / "big.mtx"
        S.save_mtx(str(path))
        whole_bytes = nnz * ingest.ENTRY_BYTES
        chunk, threads = 8192, 2
        for nproc in (4, 8):
            shards = [
                ingest.load_mtx_partitioned(
                    path, nproc, k, mode="repair",
                    chunk_bytes=chunk, threads=threads,
                )
                for k in range(nproc)
            ]
            for s in shards:
                # The documented bound: kept triplets (≤ ~3x for the
                # pre-sanitize block + the concat transient) plus the
                # in-flight parse buffers (raw chunk + its ~24B/entry
                # float64 parse array per thread) plus a fixed slack.
                local_cap = 3 * ingest.ENTRY_BYTES * (nnz // nproc + 1)
                inflight_cap = threads * 8 * chunk
                bound = local_cap + inflight_cap + (1 << 16)
                assert s.report["peak_bytes"] <= bound, (
                    nproc, s.proc_id, s.report["peak_bytes"], bound,
                )
                # And the whole point: well below the full matrix
                # (the ~2x-local concat transient is inside the bound,
                # so the margin grows linearly with p).
                assert s.report["peak_bytes"] < 2.6 * whole_bytes / nproc
        peaks4 = [s.report["peak_bytes"] for s in (
            ingest.load_mtx_partitioned(path, 4, k, mode="repair",
                                        chunk_bytes=chunk, threads=threads)
            for k in range(4)
        )]
        peaks8 = [s.report["peak_bytes"] for s in (
            ingest.load_mtx_partitioned(path, 8, k, mode="repair",
                                        chunk_bytes=chunk, threads=threads)
            for k in range(8)
        )]
        # Halving the shard roughly halves the peak (generous band:
        # the in-flight buffers are p-independent).
        assert max(peaks8) < 0.8 * max(peaks4)


class TestPartitionedGenerators:
    @pytest.mark.parametrize("nproc", [2, 3])
    def test_erdos_renyi_p_invariant(self, nproc):
        mk = lambda p, k: ingest.erdos_renyi_partitioned(  # noqa: E731
            128, 96, 4, p, k, seed=3, values="normal", chunk_edges=100,
        )
        one = mk(1, 0).coo
        multi = ingest.assemble([mk(nproc, k) for k in range(nproc)])
        _assert_bit_identical(one, multi)
        assert one.nnz > 0

    def test_rmat_p_invariant_and_bounded(self):
        mk = lambda p, k: ingest.rmat_partitioned(  # noqa: E731
            8, 4, p, k, seed=3, chunk_edges=128,
        )
        one = mk(1, 0).coo
        shards = [mk(4, k) for k in range(4)]
        _assert_bit_identical(one, ingest.assemble(shards))
        full_bytes = one.nnz * ingest.ENTRY_BYTES
        for s in shards:
            # Kept triplets scale 1/p; the two O(M) rename permutations
            # (8B ints, M = 256) are the documented constant.
            assert s.report["peak_bytes"] <= (
                3 * ingest.ENTRY_BYTES * (one.nnz // 4 + 1)
                + 4 * 128 * ingest.ENTRY_BYTES  # chunk in flight
                + 2 * 8 * 256 + (1 << 14)
            )
            assert s.report["peak_bytes"] < full_bytes + 2 * 8 * 256 + (1 << 14)

    def test_native_and_numpy_chunk_parsers_bit_agree(self):
        """The GIL-releasing native tokenizer and the numpy fallback
        must produce identical triplets — bit-for-bit doubles — or the
        partitioned loader's bit-identity contract would depend on
        which parser happened to build."""
        if not native.available():
            pytest.skip("native layer unavailable (no toolchain)")
        import io

        buf = (
            b"3 1 0.1000000000000000055511151231257827\n"
            b"1 2 -7.25e-3\n"
            b"\n"
            b"2 3 1e308\n"
        )
        nr, nc, nv = native.parse_triplets(buf)
        arr = np.loadtxt(io.BytesIO(buf), ndmin=2)
        np.testing.assert_array_equal(nr, arr[:, 0].astype(np.int64) - 1)
        np.testing.assert_array_equal(nc, arr[:, 1].astype(np.int64) - 1)
        np.testing.assert_array_equal(nv, arr[:, 2])
        # Pattern (2-column) form.
        pr, pc, pv = native.parse_triplets(b"1 1\n2 5\n", pattern=True)
        np.testing.assert_array_equal(pr, [0, 1])
        np.testing.assert_array_equal(pc, [0, 4])
        np.testing.assert_array_equal(pv, [1.0, 1.0])

    def test_generator_shard_strategy_ingest(self):
        """A generated shard's ``.coo`` is a valid strategy input (the
        elastic drill's data path): global frame, local rows only."""
        sh = ingest.erdos_renyi_partitioned(96, 80, 4, 2, 1, seed=5,
                                            values="normal", chunk_edges=64)
        assert sh.M == 96 and sh.N == 80
        assert sh.coo.rows.min() >= sh.row0
        assert sh.coo.rows.max() < sh.row1
