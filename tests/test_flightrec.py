"""Flight recorder: span ring, anomaly-triggered dumps, CLI end to end.

The contract (ISSUE 8 acceptance): a watchdog-fired anomaly produces a
flight-record file whose path appears in BOTH the anomaly trace event
and the bench record's ``anomalies`` summary — and the dump itself
carries the span ring, global metrics, and any registered telemetry
sources from the moment it fired.
"""

import json

import pytest

from distributed_sddmm_tpu.obs import (
    flightrec, metrics as obs_metrics, trace, watchdog,
)


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv("DSDDMM_TRACE", raising=False)
    monkeypatch.delenv("DSDDMM_FLIGHTREC", raising=False)
    monkeypatch.delenv("DSDDMM_WATCHDOG", raising=False)
    watchdog.disable()
    flightrec.disable()
    trace.disable()
    yield
    watchdog.disable()
    flightrec.disable()
    trace.disable()


class TestSpanRing:
    def test_bounded_rotation(self):
        ring = trace.arm_ring(4)
        for i in range(10):
            trace.event("tick", i=i)
        recs = ring.records()
        assert len(recs) == 4
        # Oldest rotated out; the count of everything ever seen remains.
        assert [r["attrs"]["i"] for r in recs] == [6, 7, 8, 9]
        assert ring.appended >= 10

    def test_memory_tracer_flows_without_file(self):
        assert not trace.enabled()
        ring = trace.arm_ring(16)
        assert trace.enabled()  # spans/events flow...
        assert trace.trace_path() is None  # ...but nothing hits disk
        with trace.span("work", x=1):
            pass
        types = [r["type"] for r in ring.records()]
        assert types == ["begin", "span"]
        trace.disarm_ring()
        assert not trace.enabled()

    def test_ring_taps_active_file_tracer(self, tmp_path):
        tr = trace.enable(tmp_path / "t.jsonl")
        ring = trace.arm_ring(16)
        trace.event("both")
        trace.disable()
        assert any(r.get("name") == "both" for r in ring.records())
        text = (tmp_path / "t.jsonl").read_text()
        assert '"both"' in text  # file tracer untouched by the ring

    def test_arm_is_idempotent(self):
        a = trace.arm_ring(8)
        b = trace.arm_ring(32)
        assert a is b and a.capacity == 8


class TestFlightRecorder:
    def _spike(self):
        wd = watchdog.enable("warn", min_samples=2, spike_factor=2.0,
                             min_abs_s=0.0)
        wd.observe("op", 0.01)
        wd.observe("op", 0.01)
        wd.observe("op", 5.0)  # spike
        return wd

    def test_anomaly_dumps_ring_and_stamps_path(self, tmp_path):
        fr = flightrec.enable(tmp_path)
        with trace.span("before", i=1):
            pass
        wd = self._spike()
        summary = wd.summary()
        paths = summary.get("snapshots")
        assert paths and len(paths) == 1
        # Stamped into the grouped record too (bench record shape).
        group = summary["anomalies"][0]
        assert group["first"]["snapshot_path"] == paths[0]
        rec = json.loads(open(paths[0]).read())
        assert rec["anomaly"]["kind"] == "step_time_spike"
        assert rec["run_id"] == fr.run_id
        assert any(r.get("name") == "before" for r in rec["ring"])
        assert "global" in rec["metrics"]
        # The anomaly trace event itself carries the path: it is in the
        # ring of a SECOND dump... simpler: the tracer ring now holds
        # the emitted anomaly event.
        anomaly_evs = [r for r in trace.ring().records()
                       if r.get("name") == "anomaly"]
        assert anomaly_evs
        assert anomaly_evs[0]["attrs"]["snapshot_path"] == paths[0]

    def test_dump_budget_bounds_files(self, tmp_path):
        flightrec.enable(tmp_path, max_dumps=2)
        wd = watchdog.enable("warn", min_samples=2, spike_factor=2.0,
                             min_abs_s=0.0)
        wd.observe("op", 0.01)
        wd.observe("op", 0.01)
        for _ in range(5):
            wd.observe("op", 5.0)
        files = list(flightrec.active().out_dir.glob("*.json"))
        assert len(files) == 2  # budget, not one per anomaly
        assert obs_metrics.GLOBAL.get("flightrec_dumps") >= 2

    def test_registered_source_lands_and_errors_contained(self, tmp_path):
        fr = flightrec.enable(tmp_path)
        fr.register_source("good", lambda: {"depth": 3})
        fr.register_source("bad", lambda: 1 / 0)
        self._spike()
        path = flightrec.active().paths[0]
        rec = json.loads(open(path).read())
        assert rec["sources"]["good"] == {"depth": 3}
        assert "ZeroDivisionError" in rec["sources"]["bad"]["error"]

    def test_profile_window_recorded(self, tmp_path, monkeypatch):
        from distributed_sddmm_tpu.obs import profiler

        calls = []
        monkeypatch.setattr(
            profiler, "capture_window",
            lambda logdir, duration_s, block: calls.append(
                (logdir, duration_s, block)) or True,
        )
        flightrec.enable(tmp_path, profile_window_s=0.1)
        self._spike()
        rec = json.loads(open(flightrec.active().paths[0]).read())
        assert rec["profile"]["started"] is True
        assert calls and calls[0][1] == 0.1 and calls[0][2] is False

    def test_env_spec_grammar(self, tmp_path):
        assert flightrec.parse_env_spec(None) == (False, None)
        assert flightrec.parse_env_spec("off") == (False, None)
        assert flightrec.parse_env_spec("1") == (True, None)
        on, root = flightrec.parse_env_spec(str(tmp_path))
        assert on and root == tmp_path

    def test_disabled_watchdog_path_unchanged(self):
        # No recorder armed: anomalies record exactly as before, no
        # snapshot_path anywhere.
        wd = self._spike()
        summary = wd.summary()
        assert "snapshots" not in summary
        assert "snapshot_path" not in summary["anomalies"][0]["first"]


class TestServeCLIEndToEnd:
    def test_bench_serve_anomaly_produces_linked_flight_record(
        self, tmp_path, capsys
    ):
        """`bench serve --watchdog --flightrec --admin-port 0` with one
        injected 0.5s straggler: the spike anomaly dumps a flight
        record whose path rides the bench record AND the anomaly trace
        event; the record carries admin_port."""
        from distributed_sddmm_tpu.bench import cli

        out_file = tmp_path / "serve.json"
        trace_file = tmp_path / "serve-trace.jsonl"
        # One delay fault at live-batch call 8: by then the per-batch
        # EWMA has its warmup baseline, so +0.5s is a guaranteed spike.
        faults = json.dumps([
            {"site": "execute:serveBatch", "kind": "delay", "at": [8],
             "param": 0.5},
        ])
        rc = cli.main([
            "serve", "--app", "als", "--log-m", "6", "--edge-factor", "6",
            "--R", "8", "--duration", "2.0", "--rate", "30",
            "--max-batch", "4", "--train-steps", "1", "--oracle-every", "0",
            "--watchdog", "warn", "--flightrec", str(tmp_path / "fr"),
            "--admin-port", "0", "--trace", str(trace_file),
            "--faults", faults, "--no-runstore", "-o", str(out_file),
        ])
        assert rc == 0
        record = json.loads(out_file.read_text().splitlines()[-1])
        assert record["admin_port"] > 0
        anomalies = record.get("anomalies") or {}
        spikes = [a for a in anomalies.get("anomalies", ())
                  if a["kind"] == "step_time_spike"]
        assert spikes, anomalies
        snap = spikes[0]["first"].get("snapshot_path")
        assert snap and json.loads(open(snap).read())["anomaly"]["kind"] \
            == "step_time_spike"
        assert snap in (anomalies.get("snapshots") or ())
        assert record["flightrec_dir"] in snap
        # The anomaly trace event carries the same path.
        events = [
            json.loads(line)
            for line in trace_file.read_text().splitlines()
            if '"anomaly"' in line
        ]
        stamped = [e for e in events
                   if e.get("type") == "event" and e.get("name") == "anomaly"
                   and e["attrs"].get("snapshot_path") == snap]
        assert stamped
