"""Chaos-schedule grammar, determinism, and fault-hook window tests
(``resilience/chaos.py``) — pure parsing/timeline logic, no processes.
"""

import pytest

from distributed_sddmm_tpu.resilience.chaos import (
    ChaosAction, ChaosSchedule,
)


class TestGrammar:
    def test_full_grammar_round_trip(self):
        spec = ("kill@0.5;wedge:r1@0.3/0.2s;partition:r0@0.6;"
                "slow:r2@0.4:80ms;corrupt:r1@0.7")
        s = ChaosSchedule.parse(spec, seed=3)
        kinds = [a.kind for a in s.actions]
        # Actions sort by fire fraction.
        assert kinds == ["wedge", "slow", "kill", "partition", "corrupt"]
        wedge = s.actions[0]
        assert wedge.target == "r1" and wedge.duration_s == pytest.approx(0.2)
        slow = s.actions[1]
        assert slow.param == pytest.approx(0.08)  # 80ms
        corrupt = s.actions[4]
        assert corrupt.param == pytest.approx(0.05)  # default frac

    def test_normalization_idempotent(self):
        spec = "corrupt@0.9:0.10;wedge@0.1/500ms;kill@0.50"
        s = ChaosSchedule.parse(spec, seed=0)
        again = ChaosSchedule.parse(s.normalized, seed=0)
        assert again.normalized == s.normalized
        assert again.actions == s.actions

    def test_time_units(self):
        s = ChaosSchedule.parse("wedge@0.5/80ms;slow@0.6:1.5s;slow@0.7:2")
        assert s.actions[0].duration_s == pytest.approx(0.08)
        assert s.actions[1].param == pytest.approx(1.5)
        assert s.actions[2].param == pytest.approx(2.0)

    def test_defaults(self):
        s = ChaosSchedule.parse("wedge@0.5;slow@0.6;corrupt@0.7")
        assert s.actions[0].duration_s == pytest.approx(1.0)
        assert s.actions[1].param == pytest.approx(0.05)
        assert s.actions[2].param == pytest.approx(0.05)

    def test_sugar(self):
        assert ChaosSchedule.parse("kill-replica").normalized == "kill@0.5"
        assert not ChaosSchedule.parse("none")
        assert not ChaosSchedule.parse("off")
        assert not ChaosSchedule.parse("")
        assert not ChaosSchedule.parse(None)

    @pytest.mark.parametrize("bad", [
        "explode@0.5",          # unknown kind
        "kill@1.5",             # frac out of range
        "kill@0.5/2s",          # kill takes no duration
        "corrupt@0.5/2s",       # corrupt takes no duration
        "kill@0.5:3",           # kill takes no param
        "wedge@0.5:3",          # wedge takes no param
        "partition@0.5:3",      # partition takes no param
        "corrupt@0.5:1.5",      # element fraction outside (0, 1]
        "corrupt@0.5:0",        # element fraction outside (0, 1]
        "wedge@",               # no fraction
        "@0.5",                 # no kind
        "kill 0.5",             # not the grammar at all
    ])
    def test_bad_specs_raise(self, bad):
        with pytest.raises(ValueError):
            ChaosSchedule.parse(bad)

    def test_render_canonical_times(self):
        a = ChaosAction(kind="wedge", frac=0.25, duration_s=0.2)
        assert a.render() == "wedge@0.25/200ms"
        b = ChaosAction(kind="slow", frac=0.4, target="r2", param=0.08)
        assert b.render() == "slow:r2@0.4:80ms"


class TestDeterminism:
    def test_timeline_is_pure(self):
        s = ChaosSchedule.parse("wedge@0.25/1s;kill@0.75", seed=11)
        t1 = s.timeline(8.0)
        t2 = ChaosSchedule.parse(s.normalized, seed=11).timeline(8.0)
        assert t1 == t2
        assert [row["t_s"] for row in t1] == [2.0, 6.0]

    def test_resolve_explicit_target_wins_when_live(self):
        s = ChaosSchedule.parse("kill:r1@0.5", seed=0)
        assert s.resolve(0, s.actions[0], ["r0", "r1", "r2"]) == "r1"

    def test_resolve_seeded_pick_is_deterministic(self):
        s = ChaosSchedule.parse("kill@0.5", seed=7)
        names = ["r2", "r0", "r1"]
        picks = {s.resolve(0, s.actions[0], list(names)) for _ in range(8)}
        assert len(picks) == 1
        # Pool order must not matter: the pick is over the sorted pool.
        assert s.resolve(0, s.actions[0], sorted(names)) in picks

    def test_resolve_differs_by_seed_or_index(self):
        a = ChaosSchedule.parse("kill@0.2;kill@0.8", seed=0)
        names = [f"r{i}" for i in range(16)]
        picks = {
            (seed, idx): ChaosSchedule.parse("kill@0.2;kill@0.8",
                                             seed=seed)
            .resolve(idx, a.actions[idx], names)
            for seed in range(4) for idx in range(2)
        }
        # Not a constant function of the pool alone.
        assert len(set(picks.values())) > 1

    def test_resolve_empty_pool(self):
        s = ChaosSchedule.parse("kill@0.5", seed=0)
        assert s.resolve(0, s.actions[0], []) is None

    def test_resolve_dead_named_target_falls_back(self):
        s = ChaosSchedule.parse("kill:r9@0.5", seed=0)
        assert s.resolve(0, s.actions[0], ["r0", "r1"]) in ("r0", "r1")


class TestFaultHookWindows:
    """The router-side wire-fault hook, driven without real processes:
    a minimal manager stand-in is enough because the windows live
    entirely inside the engine."""

    class _StubManager:
        _replicas: dict = {}

        def replicas(self, role=None):
            return []

    def _engine(self, spec, duration=10.0):
        from distributed_sddmm_tpu.resilience.chaos import ChaosEngine

        return ChaosEngine(ChaosSchedule.parse(spec),
                           self._StubManager(), duration_s=duration)

    def test_partition_window_drops(self):
        eng = self._engine("partition:r1@0.0/5s")
        action = eng.schedule.actions[0]
        event = {}
        eng._do_partition(action, "r1", event)
        assert eng.fault_hook("r1") == {"drop": True}
        assert eng.fault_hook("r0") is None

    def test_slow_window_delays(self):
        eng = self._engine("slow:r1@0.0:80ms")
        eng._do_slow(eng.schedule.actions[0], "r1", {})
        act = eng.fault_hook("r1")
        assert act == {"delay_s": pytest.approx(0.08)}

    def test_expired_window_is_inert(self):
        eng = self._engine("partition:r1@0.0/5s")
        eng._do_partition(eng.schedule.actions[0], "r1", {})
        with eng._lock:
            eng._windows[0]["t1"] = eng._windows[0]["t0"]  # expire now
        assert eng.fault_hook("r1") is None

    def test_close_clears_windows_and_hook(self):
        class _Router:
            fault_hook = None

        eng = self._engine("partition:r1@0.0")
        router = _Router()
        eng.router = router
        eng.start()
        eng._do_partition(eng.schedule.actions[0], "r1", {})
        assert router.fault_hook is not None
        eng.close()
        assert router.fault_hook is None
        assert eng.fault_hook("r1") is None
