"""Tier-1 smoke for the closed-loop tuner (ISSUE 12 satellite).

Runs ``scripts/tune_smoke.py`` as a subprocess — the end-to-end adapt
demo: a deliberately bad (generic-encoding) incumbent under a faulted
serve load with the background tuner armed must detect the gap from the
live gauges, shadow-validate the banked challenger bit-identically, and
hot-swap it mid-load with ZERO request-path compiles and a finite
``time_to_adapt_s``; a corrupted shadow replay must block promotion and
dump a flight record. Exit contract 0 (all green) / 2 (any check red).
"""

import json
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]
SCRIPT = REPO / "scripts" / "tune_smoke.py"


def test_tune_smoke_script(tmp_path):
    out = tmp_path / "tune_smoke.json"
    proc = subprocess.run(
        [sys.executable, str(SCRIPT), "-o", str(out)],
        capture_output=True, text=True, timeout=600, cwd=REPO,
        env={
            "PATH": "/usr/bin:/bin",
            "HOME": "/tmp",
            "JAX_PLATFORMS": "cpu",
            "DSDDMM_RUNSTORE": "0",
            "DSDDMM_PROGRAMS": "0",
        },
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(out.read_text())
    assert report["ok"] is True
    checks = {c["name"]: c for c in report["checks"]}

    adapt = checks["adapt"]
    assert adapt["promotions"] >= 1
    assert adapt["variant"]  # the banked challenger landed
    assert adapt["time_to_adapt_s"] > 0.0
    assert adapt["bit_identical_across_swap"] is True
    assert adapt["request_path_compiles"] == 0
    assert adapt["oracle_failures"] == 0
    assert adapt["faults_fired"] > 0  # the load really was faulted
    assert adapt["plan_cached"] is True

    mismatch = checks["mismatch"]
    assert mismatch["mismatches"] >= 1
    assert mismatch["flight_records"] >= 1
    assert mismatch["ladder_swaps"] == 0  # promotion blocked


def test_exit_code_contract():
    """The 0/2 contract without a second subprocess run."""
    sys.path.insert(0, str(REPO / "scripts"))
    try:
        import tune_smoke
    finally:
        sys.path.pop(0)
    assert tune_smoke.exit_code({"ok": True}) == 0
    assert tune_smoke.exit_code({"ok": False}) == 2
    assert tune_smoke.exit_code({}) == 2
