"""Multi-tenant QoS contract tests (PR 16): tenant declaration,
stride-scheduled weighted-fair dequeue, per-tenant shed accounting, and
the recorder/SLO plumbing the per-tenant gate axes read.

The load-bearing properties: a single tenant degenerates to the exact
FIFO the engine always had; under contention tenants drain in weight
proportion; a tenant waking from idle cannot replay service it never
asked for; sheds and submissions are attributed to the tenant that
caused them.
"""

import pytest

from distributed_sddmm_tpu.serve import (
    DEFAULT_TENANT, RequestQueue, ShedError, SLOSpec, TenantSpec,
    parse_tenants,
)
from distributed_sddmm_tpu.serve.queue import Request
from distributed_sddmm_tpu.serve.slo import LatencyRecorder, attach_tenant_slo


def _tenants(*pairs):
    return [TenantSpec(name, weight=w) for name, w in pairs]


class TestTenantSpec:
    def test_bad_names_rejected(self):
        for bad in ("", "a:b", "a;b", "a,b", "a=b", "a b"):
            with pytest.raises(ValueError):
                TenantSpec(bad)

    def test_nonpositive_weight_rejected(self):
        with pytest.raises(ValueError):
            TenantSpec("t", weight=0.0)
        with pytest.raises(ValueError):
            TenantSpec("t", weight=-1.0)

    def test_duplicate_declaration_rejected(self):
        with pytest.raises(ValueError):
            RequestQueue(tenants=_tenants(("a", 1), ("a", 2)))


class TestParseTenants:
    def test_grammar(self):
        t = parse_tenants("premium:3:p99_ms=250,err_rate=0.01;batch:1")
        assert set(t) == {"premium", "batch"}
        assert t["premium"].weight == 3.0
        assert t["premium"].slo.p99_ms == 250.0
        assert t["premium"].slo.err_rate == 0.01
        assert t["batch"].weight == 1.0
        assert t["batch"].slo is None

    def test_weight_defaults_to_one(self):
        t = parse_tenants("solo")
        assert t["solo"].weight == 1.0

    def test_empty_spec_is_none(self):
        assert parse_tenants(None) is None
        assert parse_tenants("") is None

    def test_duplicate_clause_raises(self):
        with pytest.raises(ValueError):
            parse_tenants("a:1;a:2")


class TestStrideScheduling:
    def test_single_tenant_exact_fifo(self):
        q = RequestQueue(max_depth=16, max_batch=16, max_wait_ms=0.0)
        reqs = [q.submit(i) for i in range(8)]
        batch = q.next_batch(timeout_s=1.0)
        assert [r.req_id for r in batch] == [r.req_id for r in reqs]
        assert all(r.tenant == DEFAULT_TENANT for r in batch)

    def test_weighted_fair_under_contention(self):
        """premium (w=3) : batch (w=1) must drain ~3:1 over any busy
        window — here, exactly 3:1 inside the first 8 slots."""
        q = RequestQueue(
            max_depth=64, max_batch=8, max_wait_ms=0.0,
            tenants=_tenants(("premium", 3), ("batch", 1)),
        )
        for i in range(16):
            q.submit(("p", i), tenant="premium")
            q.submit(("b", i), tenant="batch")
        batch = q.next_batch(timeout_s=1.0)
        kinds = [r.payload[0] for r in batch]
        assert kinds.count("p") == 6 and kinds.count("b") == 2
        # FIFO within each tenant class.
        assert [r.payload[1] for r in batch if r.payload[0] == "p"] \
            == [0, 1, 2, 3, 4, 5]

    def test_idle_tenant_wakes_without_credit(self):
        """A tenant idle while others drained must not burst past its
        weight when it wakes: its pass catches up to the busy floor."""
        q = RequestQueue(
            max_depth=64, max_batch=4, max_wait_ms=0.0,
            tenants=_tenants(("a", 1), ("b", 1)),
        )
        for i in range(12):
            q.submit(("a", i), tenant="a")
        q.next_batch(timeout_s=1.0)  # 4 "a" drains advance a's pass
        for i in range(8):
            q.submit(("b", i), tenant="b")
        batch = q.next_batch(timeout_s=1.0)
        kinds = [r.payload[0] for r in batch]
        # Equal weights → the woken tenant alternates, it does not
        # monopolize the batch on banked virtual time.
        assert kinds.count("a") == 2 and kinds.count("b") == 2

    def test_unknown_tenant_rejected(self):
        q = RequestQueue(tenants=_tenants(("a", 1)))
        with pytest.raises(ValueError, match="unknown tenant"):
            q.submit("x", tenant="typo")

    def test_per_tenant_shed_and_submit_counters(self):
        q = RequestQueue(
            max_depth=2, max_batch=2, max_wait_ms=0.0,
            tenants=_tenants(("a", 1), ("b", 1)),
        )
        q.submit("x", tenant="a")
        q.submit("y", tenant="b")
        with pytest.raises(ShedError):
            q.submit("z", tenant="b")
        assert q.tenant_submitted == {"a": 1, "b": 1}
        assert q.tenant_shed == {"a": 0, "b": 1}
        assert q.shed_count == 1
        assert q.tenant_depths() == {"a": 1, "b": 1}


class TestTenantRecorder:
    @staticmethod
    def _reply(recorder, tenant, total_ms=5.0):
        req = Request(0, None, tenant=tenant)
        req.t_enqueue = 0.0
        req.t_admit = req.t_execute = 1e-4
        req.t_reply = total_ms / 1e3
        recorder.record_reply(req)

    def test_summary_tenant_table(self):
        rec = LatencyRecorder()
        self._reply(rec, "premium")
        self._reply(rec, "premium")
        self._reply(rec, "batch", total_ms=50.0)
        rec.record_shed("batch")
        rec.record_error("premium")
        s = rec.summary()
        t = s["tenant"]
        assert t["premium"]["completed"] == 2
        assert t["premium"]["errors"] == 1
        assert t["batch"]["shed_count"] == 1
        assert t["batch"]["shed_rate"] == pytest.approx(0.5)
        assert t["batch"]["request_hist"]["counts"]

    def test_default_only_keeps_prefleet_shape(self):
        """Single-tenant summaries must not grow a tenant table — the
        pre-PR-16 record shape is a compatibility contract."""
        rec = LatencyRecorder()
        self._reply(rec, DEFAULT_TENANT)
        assert "tenant" not in rec.summary()

    def test_attach_tenant_slo_judges_each_class(self):
        rec = LatencyRecorder()
        self._reply(rec, "premium", total_ms=500.0)
        summary = rec.summary()
        tenants = {
            "premium": TenantSpec(
                "premium", weight=3, slo=SLOSpec.parse("p99_ms=100"),
            ),
            "idle": TenantSpec("idle", weight=1,
                               slo=SLOSpec.parse("p99_ms=100")),
        }
        attach_tenant_slo(summary, tenants)
        prem = summary["tenant"]["premium"]
        assert prem["weight"] == 3
        assert prem["burn_rate"] > 1.0  # 500ms against a 100ms p99
        # Declared-but-idle tenants get a zeroed, judged cell so the
        # record's tenant table always matches the declaration.
        idle = summary["tenant"]["idle"]
        assert idle["requests"] == 0
        assert idle["slo"]["p99_ms"] == 100.0
