import numpy as np
import pytest

from distributed_sddmm_tpu.common import MatMode
from distributed_sddmm_tpu.parallel.sparse_shift_15d import SparseShift15D
from distributed_sddmm_tpu.utils import oracle
from distributed_sddmm_tpu.utils.coo import HostCOO


def _problem(M=64, N=48, seed=0):
    return HostCOO.erdos_renyi(M, N, 4, seed=seed, values="normal")


def _dense_inputs(alg):
    A = alg.dummy_initialize(MatMode.A)
    B = alg.dummy_initialize(MatMode.B)
    A_host = oracle.dummy_dense(alg.M_pad, alg.R)
    B_host = oracle.dummy_dense(alg.N_pad, alg.R)
    return A, B, A_host, B_host


CONFIGS = [1, 2, 4, 8]  # c values on the 8-device CPU mesh


def test_dense_representation_roundtrip():
    S = _problem()
    alg = SparseShift15D(S, R=8, c=2)
    A = alg.dummy_initialize(MatMode.A)
    assert A.shape == alg.dense_shape(MatMode.A)
    np.testing.assert_allclose(
        alg.host_a(A), oracle.dummy_dense(alg.M_pad, 8)[: alg.M], rtol=1e-6
    )
    rng = np.random.default_rng(0)
    X = rng.standard_normal((S.M, 8))
    np.testing.assert_allclose(alg.host_a(alg.put_a(X)), X, rtol=1e-6)


@pytest.mark.parametrize("c", CONFIGS)
def test_sddmm_a(c):
    S = _problem()
    alg = SparseShift15D(S, R=8, c=c)
    A, B, A_host, B_host = _dense_inputs(alg)
    out = alg.sddmm_a(A, B, alg.scatter_s_values(S.vals))
    np.testing.assert_allclose(
        alg.gather_s_values(out), oracle.sddmm(S, A_host, B_host), rtol=1e-4
    )


@pytest.mark.parametrize("c", [1, 2, 8])
def test_sddmm_b(c):
    S = _problem()
    alg = SparseShift15D(S, R=8, c=c)
    A, B, A_host, B_host = _dense_inputs(alg)
    out = alg.sddmm_b(A, B, alg.scatter_st_values(S.transpose().vals))
    np.testing.assert_allclose(
        alg.gather_st_values(out),
        oracle.sddmm(S.transpose(), B_host, A_host),
        rtol=1e-4,
    )


@pytest.mark.parametrize("c", CONFIGS)
def test_spmm_a(c):
    S = _problem()
    alg = SparseShift15D(S, R=8, c=c)
    A, B, A_host, B_host = _dense_inputs(alg)
    out = alg.spmm_a(A, B, alg.scatter_s_values(S.vals))
    np.testing.assert_allclose(
        alg.host_a(out)[: S.M], oracle.spmm_a(S, B_host), rtol=1e-4, atol=1e-3
    )


@pytest.mark.parametrize("c", [1, 4])
def test_spmm_b(c):
    S = _problem()
    alg = SparseShift15D(S, R=8, c=c)
    A, B, A_host, B_host = _dense_inputs(alg)
    out = alg.spmm_b(A, B, alg.scatter_st_values(S.transpose().vals))
    np.testing.assert_allclose(
        alg.host_b(out)[: S.N], oracle.spmm_b(S, A_host), rtol=1e-4, atol=1e-3
    )


def test_fused_spmm_chained():
    """Base-class fused (sddmm then spmm with the mid values)."""
    S = _problem()
    alg = SparseShift15D(S, R=8, c=2)
    A, B, A_host, B_host = _dense_inputs(alg)
    out, mid = alg.fused_spmm(A, B, alg.scatter_s_values(S.vals), MatMode.A)
    np.testing.assert_allclose(
        alg.host_a(out)[: S.M],
        oracle.fused_spmm_a(S, A_host, B_host),
        rtol=1e-3,
        atol=1e-2,
    )


def test_rolled_matches_unrolled():
    S = _problem()
    res = []
    for unroll in (True, False):
        alg = SparseShift15D(S, R=8, c=2, unroll=unroll)
        A, B, _, _ = _dense_inputs(alg)
        out = alg.spmm_a(A, B, alg.scatter_s_values(S.vals))
        res.append(alg.host_a(out))
    np.testing.assert_allclose(res[0], res[1], rtol=1e-5)


def test_cross_algorithm_fingerprints():
    """Fingerprint protocol across DIFFERENT algorithms (scratch.cpp:26-76)."""
    from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D

    S = _problem()
    fps = []
    for alg in (
        SparseShift15D(S, R=8, c=2),
        DenseShift15D(S, R=8, c=4, fusion_approach=1),
        DenseShift15D(S, R=8, c=1, fusion_approach=2),
    ):
        A = alg.dummy_initialize(MatMode.A)
        B = alg.dummy_initialize(MatMode.B)
        out = alg.spmm_a(A, B, alg.scatter_s_values(S.vals))
        fps.append(alg.fingerprint(alg.host_a(out)[: S.M]))
    np.testing.assert_allclose(fps, fps[0], rtol=1e-5)


def test_r_divisibility_check():
    S = _problem()
    with pytest.raises(ValueError):
        SparseShift15D(S, R=7, c=1)  # p/c = 8 does not divide 7
    alg = SparseShift15D(S, R=8, c=2)
    with pytest.raises(ValueError):
        alg.set_r_value(6)  # p/c = 4 does not divide 6
    assert alg.r_split and alg.r_split_axis == "rows"
