"""Closed-loop tuner tests (PR 12): signal mining, realized re-ranking,
counted trials, shadow safety, and the hot-swap contract.

The hot-swap safety pins (ISSUE 12 satellite):

* a shadow mismatch blocks promotion and dumps a flight record;
* a swapped-in ladder serves bit-identical replies;
* a stale/evicted challenger program can never be promoted (variant
  generation refused at session construction AND at swap; challenger
  store keys carry the ``v<variant>`` segment so they can never alias
  the incumbent's entries).
"""

import json
import os

import numpy as np
import pytest

from distributed_sddmm_tpu.autotune.candidates import (
    Candidate,
    enumerate_candidates,
    rank_candidates,
    rank_candidates_realized,
)
from distributed_sddmm_tpu.autotune.cache import PlanCache
from distributed_sddmm_tpu.autotune.fingerprint import Problem
from distributed_sddmm_tpu.models.als import DistributedALS
from distributed_sddmm_tpu.ops.pallas_kernels import PallasKernel
from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
from distributed_sddmm_tpu.serve import ALSFoldInTopK, ServingEngine
from distributed_sddmm_tpu.tuner import (
    BackgroundTuner,
    ShadowSession,
    StaleChallenger,
    TunerConfig,
    counted_trial,
    mine_engine,
    mine_watchdog,
    mine_runstore,
)
from distributed_sddmm_tpu.tuner.loop import factory_name
from distributed_sddmm_tpu.tuner.retune import counted_pad_frac, retune
from distributed_sddmm_tpu.tuner.signals import engine_problem, realized_info
from distributed_sddmm_tpu.utils.coo import HostCOO

#: The smoke scenario: skewed R-mat, small nnz/row bucket — the
#: fingerprint selects a banked variant and the counted win is >10%.
LOG_M, EDGE_FACTOR, R = 10, 4, 8


@pytest.fixture(scope="module")
def stack():
    """One warm generic-Pallas ALS serving stack shared by the module
    (strategy build + ladder warmup dominate this suite's cost)."""
    S = HostCOO.rmat(log_m=LOG_M, edge_factor=EDGE_FACTOR, seed=0)
    alg = DenseShift15D(
        S, R=R, c=1, fusion_approach=2,
        kernel=PallasKernel(precision="f32", interpret=True),
    )
    model = DistributedALS(alg, S_host=S)
    model.initialize_embeddings()
    workload = ALSFoldInTopK(model, k=5, item_buckets=(8,),
                             ingest_rows=False)
    engine = ServingEngine(workload, max_batch=2, max_depth=32,
                           max_wait_ms=2.0)
    engine.warmup()
    yield S, model, workload, engine
    engine.detach_mirror()


@pytest.fixture()
def problem(stack):
    S, model, _w, _e = stack
    return Problem.from_coo(S, model.d_ops.R)


# --------------------------------------------------------------------- #
# Signals
# --------------------------------------------------------------------- #


class TestSignals:
    def test_generic_incumbent_with_high_gauge_signals(self, stack):
        _S, _m, _w, engine = stack
        info = realized_info(engine)
        assert info["variant"] is None
        assert info["padded_lane_frac"] > 0.25
        sigs = mine_engine(engine, lane_frac_threshold=0.25)
        assert len(sigs) == 1
        assert sigs[0].kind == "padded_lanes"
        assert sigs[0].severity == pytest.approx(
            info["padded_lane_frac"]
        )

    def test_threshold_respected(self, stack):
        _S, _m, _w, engine = stack
        assert mine_engine(engine, lane_frac_threshold=0.99) == []

    def test_engine_problem_resolves(self, stack):
        S, _m, _w, engine = stack
        prob = engine_problem(engine)
        assert (prob.M, prob.nnz, prob.R) == (S.M, S.nnz, R)

    def test_watchdog_waste_anomalies_signal(self):
        from distributed_sddmm_tpu.obs.watchdog import Watchdog

        wd = Watchdog(mode="warn")
        wd.check_xla_costs(
            {"fusedSpMM": {"calls": 4, "flops": 4.0}},
            {"fusedSpMM": {"flops_per_call": 64.0}},  # 64x waste
        )
        sigs = mine_watchdog(wd)
        assert [s.kind for s in sigs] == ["xla_waste"]
        # The cursor suppresses already-acted-on anomalies.
        assert mine_watchdog(wd, since=len(wd.events)) == []

    def test_mine_xla_live_waste_signal(self, monkeypatch):
        """The live xla_waste read: flags compiled-FLOPs blowup over
        dispatched ops without recording watchdog anomalies, and the
        caller-owned `seen` set dedups across scans."""
        from types import SimpleNamespace

        from distributed_sddmm_tpu import programs
        from distributed_sddmm_tpu.obs.metrics import OpMetrics
        from distributed_sddmm_tpu.tuner.signals import mine_xla

        m = OpMetrics()
        m.record("fusedSpMM", kernel_s=0.01, flops=100.0)
        eng = SimpleNamespace(workload=SimpleNamespace(
            model=SimpleNamespace(d_ops=SimpleNamespace(metrics=m))
        ))
        monkeypatch.setattr(
            programs, "xla_cost_summary",
            lambda ops, since=0: {
                "ops": {"fusedSpMM": {"flops_per_call": 1e9}}
            },
        )
        seen = set()
        sigs = mine_xla(eng, seen=seen)
        assert [s.kind for s in sigs] == ["xla_waste"]
        assert sigs[0].op == "fusedSpMM"
        assert mine_xla(eng, seen=seen) == []  # deduped
        # Under the waste band: silent.
        monkeypatch.setattr(
            programs, "xla_cost_summary",
            lambda ops, since=0: {
                "ops": {"fusedSpMM": {"flops_per_call": 200.0}}
            },
        )
        assert mine_xla(eng, seen=set()) == []

    def test_runstore_gap_signal(self, problem, tmp_path):
        from distributed_sddmm_tpu.obs.store import RunStore

        store = RunStore(tmp_path / "rs")
        rec = {
            "app": "vanilla", "algorithm": "15d_fusion2", "R": problem.R,
            "c": 1, "fused": True, "elapsed": 1.0,
            "overall_throughput": 0.5, "metrics": {},
            "alg_info": {"m": problem.M, "n": problem.N,
                         "nnz": problem.nnz, "p": 8},
        }
        doc = store.ingest_record(dict(rec), source="er")
        doc["key"] = "fp-under-test"
        # predicted 1 GFLOP/s-equivalent pair time; realized 0.5 -> gap.
        flops = 4.0 * problem.nnz * problem.R
        predicted_ms = flops / (10.0 * 1e9) * 1e3  # model says 10 GF/s
        rows = store.history()
        assert rows  # the store indexed the record
        sigs = mine_runstore(
            store, rows[0]["key"], problem, predicted_ms, gap_factor=0.5
        )
        assert sigs and sigs[0].kind == "runstore_gap"
        # A realized number at/over the gap threshold stays silent.
        assert mine_runstore(
            store, rows[0]["key"], problem, predicted_ms, gap_factor=0.01
        ) == []


# --------------------------------------------------------------------- #
# Realized re-ranking + counted trials (autotune/ + tuner/retune.py)
# --------------------------------------------------------------------- #


class TestRetune:
    def test_counted_banked_beats_generic_on_skewed(self, stack, problem):
        S = stack[0]
        gen = Candidate("15d_fusion2", 1, kernel="pallas")
        from distributed_sddmm_tpu.codegen import variant_ids_for

        vid = variant_ids_for(problem)[0]
        banked = Candidate("15d_fusion2", 1, kernel="pallas", variant=vid)
        assert counted_pad_frac(S, banked) < counted_pad_frac(S, gen)
        tg = counted_trial(S, problem, gen, 1, 0)["overall_throughput"]
        tb = counted_trial(S, problem, banked, 1, 0)["overall_throughput"]
        assert tb > tg * 1.05

    def test_xla_candidates_count_zero_lanes(self, stack, problem):
        S = stack[0]
        assert counted_pad_frac(S, Candidate("15d_fusion2", 1)) == 0.0

    def test_realized_reranking_prefers_banked(self, problem):
        cands = enumerate_candidates(problem, 8, ("pallas",))
        cands = [c for c in cands if c.algorithm == "15d_fusion2"
                 and c.c == 1]
        assert any(c.variant for c in cands)
        # Without realized data: identical to the model ranking.
        plain = rank_candidates_realized(problem, cands, 8)
        assert [c for c, _ in plain] == [
            c for c, _ in rank_candidates(problem, cands, 8)
        ]
        # With a high realized generic pad gauge, the banked variant
        # must lead the measure-first ordering.
        ranked = rank_candidates_realized(
            problem, cands, 8,
            realized={"variant": None, "padded_lane_frac": 0.9},
        )
        assert ranked[0][0].variant is not None

    def test_realized_data_for_banked_incumbent_is_ignored(self, problem):
        cands = enumerate_candidates(problem, 8, ("pallas",))
        a = rank_candidates_realized(
            problem, cands, 8,
            realized={"variant": "v1.rb4.rs", "padded_lane_frac": 0.9},
        )
        b = rank_candidates(problem, cands, 8)
        assert [c for c, _ in a] == [c for c, _ in b]

    def test_retune_returns_banked_challenger(self, stack, problem):
        S, model, _w, engine = stack
        tuner = BackgroundTuner(
            engine, config=TunerConfig(trial="counted"),
            plan_cache=PlanCache("/nonexistent-never-written"),
        )
        incumbent = tuner.incumbent_plan()
        assert incumbent.algorithm == "15d_fusion2"
        assert incumbent.kernel == "pallas"
        ch = retune(
            problem, incumbent, S,
            realized=realized_info(engine),
            hot_swappable=True, trial_fn=counted_trial,
        )
        assert ch is not None and ch.variant is not None
        assert ch.source == "tuned"
        # Hot-swappable space: same algorithm, c, kernel family.
        assert (ch.algorithm, ch.c, ch.kernel) == (
            incumbent.algorithm, incumbent.c, incumbent.kernel
        )

    def test_factory_name_round_trip(self, stack):
        assert factory_name(stack[1].d_ops) == "15d_fusion2"


# --------------------------------------------------------------------- #
# Shadow safety + hot-swap contract
# --------------------------------------------------------------------- #


def _mirror_one_group(engine, workload, n=2, seed=5):
    rng = np.random.default_rng(seed)
    payloads = [workload.clamp(workload.sample_payload(rng))
                for _ in range(n)]
    replies = engine.execute_now(payloads)
    return payloads, replies


class TestShadowSafety:
    def test_clean_shadow_validates_bit_identically(self, stack, problem):
        from distributed_sddmm_tpu.codegen import variant_ids_for

        _S, _m, workload, engine = stack
        vid = variant_ids_for(problem)[0]
        shadow = ShadowSession(engine, vid)
        assert shadow.warm() == 2
        payloads, replies = _mirror_one_group(engine, workload)
        shadow.offer(payloads, replies, 2, 8)
        assert shadow.drain() == 1
        assert shadow.mismatches == 0 and shadow.ok == len(payloads)
        assert shadow.clean(len(payloads))

    def test_mismatch_blocks_promotion_and_dumps_flight_record(
        self, stack, problem, tmp_path,
    ):
        from distributed_sddmm_tpu.codegen import variant_ids_for
        from distributed_sddmm_tpu.obs import flightrec
        from distributed_sddmm_tpu.resilience import FaultPlan, fault_plan

        _S, _m, workload, engine = stack
        swaps_before = engine.stats()["ladder_swaps"]
        vid = variant_ids_for(problem)[0]
        fr = flightrec.enable(tmp_path / "fr")
        try:
            shadow = ShadowSession(engine, vid)
            shadow.warm()
            payloads, replies = _mirror_one_group(engine, workload)
            shadow.offer(payloads, replies, 2, 8)
            plan = FaultPlan.from_spec(
                '[{"site": "output:tunerShadow", "kind": "nan", '
                '"prob": 1.0}]'
            )
            with fault_plan(plan):
                shadow.drain()
        finally:
            flightrec.disable()
        assert shadow.mismatches == 1
        assert not shadow.clean(1)
        assert shadow.mismatch_detail["reason"] == "reply_diverged"
        # The flight record landed and is valid JSON naming the anomaly.
        assert len(fr.paths) == 1
        dump = json.loads(
            open(fr.paths[0]).read()  # noqa: SIM115
        )
        assert dump["anomaly"]["kind"] == "tuner_shadow_mismatch"
        # Promotion blocked: the live ladder was never touched.
        assert engine.stats()["ladder_swaps"] == swaps_before
        assert workload.kernel_variant is None

    def test_stale_variant_refused_at_session_and_swap(self, stack):
        _S, _m, _w, engine = stack
        with pytest.raises(StaleChallenger):
            ShadowSession(engine, "v99.rb8.rm")
        cells = {
            (bb, ib): object()
            for bb in engine.batch_buckets
            for ib in engine.workload.inner_buckets
        }
        with pytest.raises(ValueError):
            engine.swap_ladder(cells, "v99.rb8.rm")
        assert engine.stats()["ladder_swaps"] == 0

    def test_partial_challenger_ladder_refused(self, stack, problem):
        from distributed_sddmm_tpu.codegen import variant_ids_for

        _S, _m, _w, engine = stack
        vid = variant_ids_for(problem)[0]
        with pytest.raises(ValueError, match="missing cells"):
            engine.swap_ladder({(1, 8): object()}, vid)

    def test_challenger_keys_never_alias_incumbent(self, stack, problem):
        from distributed_sddmm_tpu.codegen import variant_ids_for
        from distributed_sddmm_tpu.programs.keys import parse_serve_key

        _S, _m, _w, engine = stack
        vid = variant_ids_for(problem)[0]
        inc = engine.program_key(2, 8, sig="abc")
        ch = engine.program_key(2, 8, sig="abc", variant=vid)
        assert inc != ch
        parsed = parse_serve_key(ch)
        assert parsed["variant"] == vid
        assert "variant" not in parse_serve_key(inc)

    def test_challenger_store_entries_isolated(self, stack, problem,
                                               tmp_path):
        """Through a real program store: incumbent and challenger warm
        under disjoint keys; evicting the challenger's entries can only
        ever force a recompile under its own key, never a foreign hit."""
        from distributed_sddmm_tpu.codegen import variant_ids_for
        from distributed_sddmm_tpu.programs import ProgramStore
        from distributed_sddmm_tpu.programs.keys import parse_serve_key

        S, model, _w, _e = stack
        store = ProgramStore(tmp_path / "programs")
        workload = ALSFoldInTopK(model, k=5, item_buckets=(8,),
                                 ingest_rows=False)
        engine = ServingEngine(workload, max_batch=2, max_depth=8,
                               max_wait_ms=2.0, program_store=store)
        engine.warmup()
        vid = variant_ids_for(problem)[0]
        shadow = ShadowSession(engine, vid)
        shadow.warm()
        keys = [row["key"] for row in store.index()]
        inc_keys = {k for k in keys
                    if "variant" not in (parse_serve_key(k) or {})}
        ch_keys = {k for k in keys
                   if (parse_serve_key(k) or {}).get("variant") == vid}
        assert inc_keys and ch_keys and not (inc_keys & ch_keys)
        # Evicted challenger entries disappear from the store without
        # touching the incumbent's.
        for k in ch_keys:
            store.evict(k)
        left = {row["key"] for row in store.index()}
        assert inc_keys <= left and not (ch_keys & left)


# --------------------------------------------------------------------- #
# The full loop: detect -> measure -> shadow -> promote
# --------------------------------------------------------------------- #


class TestFullCycle:
    def test_promotion_is_bit_identical_and_compile_free(self, tmp_path):
        """A dedicated stack (the swap mutates workload/engine state the
        shared fixture must keep pristine)."""
        S = HostCOO.rmat(log_m=LOG_M, edge_factor=EDGE_FACTOR, seed=0)
        alg = DenseShift15D(
            S, R=R, c=1, fusion_approach=2,
            kernel=PallasKernel(precision="f32", interpret=True),
        )
        model = DistributedALS(alg, S_host=S)
        model.initialize_embeddings()
        workload = ALSFoldInTopK(model, k=5, item_buckets=(8,),
                                 ingest_rows=False)
        engine = ServingEngine(workload, max_batch=2, max_depth=32,
                               max_wait_ms=2.0)
        cache = PlanCache(tmp_path / "plans")
        tuner = BackgroundTuner(
            engine,
            config=TunerConfig(interval_s=0.01, lane_frac=0.25,
                               shadow_samples=2, cooldown_s=0.0,
                               trial="counted"),
            plan_cache=cache,
        )
        engine.warmup()
        stats0 = engine.stats()
        rng = np.random.default_rng(3)
        probes = [workload.sample_payload(rng) for _ in range(4)]
        before = [engine.execute_now([p])[0] for p in probes]

        assert tuner.step() == "shadow"  # scan -> measure -> shadow arm
        assert tuner.challenger.variant is not None
        # Mirror traffic through the real serve path, then drain.
        engine.start(warmup=False)
        try:
            import time

            for _ in range(40):
                for p in probes:
                    engine.submit(p)
                time.sleep(0.05)
                if tuner.step() == "scan":
                    break
        finally:
            engine.stop()
        assert len(tuner.promotions) == 1, tuner.rejects
        promo = tuner.promotions[0]
        assert promo["time_to_adapt_s"] > 0
        assert tuner.time_to_adapt_s == promo["time_to_adapt_s"]
        # Bit-identical replies across the swap; no request-path
        # compiles; the ladder swap is recorded.
        after = [engine.execute_now([p])[0] for p in probes]
        assert all(
            np.array_equal(a["items"], b["items"])
            and np.array_equal(a["scores"], b["scores"])
            for a, b in zip(before, after)
        )
        stats1 = engine.stats()
        assert stats1["live_compiles"] == stats0["live_compiles"]
        assert stats1["ladder_swaps"] == 1
        assert workload.kernel_variant == promo["plan"]["variant"]
        # The plan cache now serves the tuned plan to the next replica.
        cached = cache.load(promo["plan"]["fingerprint_key"])
        assert cached["variant"] == promo["plan"]["variant"]
        assert cached["source"] == "tuned"
        # Telemetry snapshot exposes the tuner state.
        from distributed_sddmm_tpu.obs.telemetry import engine_snapshot

        snap = engine_snapshot(engine)
        assert snap["tuner"]["promotions"] == 1
        assert snap["tuner"]["time_to_adapt_s"] == promo["time_to_adapt_s"]
        # The serve-record summary carries the promotions list.
        summary = tuner.summary()
        assert summary["promotions"] and summary["time_to_adapt_s"]
        # Convergence: with the workload restamped and model.plan set,
        # the same gap must NOT re-trigger — the next scan finds no
        # signal and arms nothing (cooldown zeroed to prove it is the
        # signal logic, not the timer, that stops the loop).
        tuner._cool_until = 0.0
        assert tuner.step() == "scan"
        assert tuner.challenger is None
        assert len(tuner.promotions) == 1
        assert tuner.incumbent_plan().variant == promo["plan"]["variant"]

    def test_no_signal_stays_idle(self, stack):
        _S, _m, _w, engine = stack
        tuner = BackgroundTuner(
            engine,
            config=TunerConfig(lane_frac=0.99, cooldown_s=0.0,
                               trial="counted", gap_factor=0.0),
            plan_cache=PlanCache("/nonexistent-never-written"),
        )
        assert tuner.step() == "scan"
        assert tuner.challenger is None and not tuner.promotions

    def test_budget_exhaustion_is_terminal(self, stack):
        """Structural signals re-fire every scan; once the measurement
        budget is gone the tuner retires instead of appending an
        identical reject every cooldown for the replica's life."""
        _S, _m, _w, engine = stack
        tuner = BackgroundTuner(
            engine,
            config=TunerConfig(lane_frac=0.25, cooldown_s=0.0,
                               budget_s=0.0, trial="counted"),
            plan_cache=PlanCache("/nonexistent-never-written"),
        )
        assert tuner.step() == "exhausted"
        assert tuner.rejects[-1]["reason"] == "measure_budget_exhausted"
        n = len(tuner.rejects)
        assert tuner.step() == "exhausted"  # terminal: a no-op
        assert len(tuner.rejects) == n

    def test_shadow_timeout_returns_mirror(self, stack, problem):
        """A shadow session whose mirrored traffic dries up must be
        abandoned, not held (with the mirror attached) forever."""
        from distributed_sddmm_tpu.codegen import variant_ids_for

        _S, _m, _w, engine = stack
        tuner = BackgroundTuner(
            engine,
            config=TunerConfig(cooldown_s=0.0, trial="counted",
                               shadow_timeout_s=0.0, shadow_samples=99),
            plan_cache=PlanCache("/nonexistent-never-written"),
        )
        shadow = ShadowSession(engine, variant_ids_for(problem)[0])
        tuner.shadow = shadow
        tuner.state = "shadow"
        engine.attach_mirror(shadow.offer)
        assert tuner.step() == "scan"
        assert tuner.rejects[-1]["reason"] == "shadow_timeout"
        assert engine._mirror is None  # mirror handed back
        assert not tuner.promotions


# --------------------------------------------------------------------- #
# Config, gate axis, CLI
# --------------------------------------------------------------------- #


class TestConfigAndSurfaces:
    def test_config_from_env(self, monkeypatch):
        monkeypatch.setenv("DSDDMM_TUNER_INTERVAL", "0.5")
        monkeypatch.setenv("DSDDMM_TUNER_LANE_FRAC", "0.4")
        monkeypatch.setenv("DSDDMM_TUNER_SHADOW_N", "9")
        monkeypatch.setenv("DSDDMM_TUNER_BUDGET", "12")
        monkeypatch.setenv("DSDDMM_TUNER_COOLDOWN", "3")
        monkeypatch.setenv("DSDDMM_TUNER_GAP", "0.7")
        monkeypatch.setenv("DSDDMM_TUNER_TRIAL", "counted")
        cfg = TunerConfig.from_env()
        assert (cfg.interval_s, cfg.lane_frac, cfg.shadow_samples,
                cfg.budget_s, cfg.cooldown_s, cfg.gap_factor,
                cfg.trial) == (0.5, 0.4, 9, 12.0, 3.0, 0.7, "counted")
        assert cfg.trial_fn() is counted_trial

    def test_time_to_adapt_gate_axis(self):
        from distributed_sddmm_tpu.obs import regress

        doc = {"record": {
            "requests": 10, "time_to_adapt_s": 2.5,
            "tuner": {"promotions": [{"time_to_adapt_s": 2.5}]},
        }}
        rows = regress.phase_stats(doc)
        assert rows["tuner:time_to_adapt"]["t_call"] == 2.5
        # Optional axis: a doc without the field compares as
        # not-measured, never "missing".
        report = regress.compare(
            {"record": {"requests": 10}}, doc_a=doc
        )
        assert (report["phases"]["tuner:time_to_adapt"]["verdict"]
                == "not-measured")
        assert report["verdict"] != "regression"
        # A slower adaptation regresses with tuner attribution.
        slow = {"record": {
            "requests": 10, "time_to_adapt_s": 25.0,
            "tuner": {"promotions": [{"time_to_adapt_s": 25.0}]},
        }}
        report = regress.compare(slow, doc_a=doc)
        row = report["phases"]["tuner:time_to_adapt"]
        assert row["verdict"] == "regression"
        assert row["attribution"] == "tuner"

    def test_tuner_counters_declared_for_export(self):
        from distributed_sddmm_tpu.obs.httpexp import KNOWN_GLOBAL_COUNTERS

        for name in ("tuner_scans", "tuner_signals", "tuner_retunes",
                     "tuner_shadow_replays", "tuner_shadow_mismatches",
                     "tuner_promotions", "tuner_rejects"):
            assert name in KNOWN_GLOBAL_COUNTERS

    def test_bench_tune_cli(self, monkeypatch, tmp_path, capsys):
        from distributed_sddmm_tpu.bench import cli

        monkeypatch.setenv("DSDDMM_PLAN_CACHE", str(tmp_path / "plans"))
        rc = cli.main([
            "tune", "6", "4", "8", "--trial", "counted", "--json",
        ])
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        assert report["incumbent"]["algorithm"]
        assert "promoted" in report

    def test_bench_tune_dry_run_writes_nothing(self, monkeypatch,
                                               tmp_path, capsys):
        """--dry-run must leave the plan cache byte-untouched — even
        get_plan's store-on-miss goes to a throwaway cache."""
        from distributed_sddmm_tpu.bench import cli

        cache_dir = tmp_path / "plans-dry"
        monkeypatch.setenv("DSDDMM_PLAN_CACHE", str(cache_dir))
        rc = cli.main([
            "tune", "6", "4", "8", "--trial", "counted", "--json",
            "--dry-run",
        ])
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        assert report["dry_run"] is True and report["promoted"] is False
        assert not (cache_dir.exists() and list(cache_dir.glob("*.json")))

    def test_tuner_knobs_registered(self):
        from distributed_sddmm_tpu.utils import envreg

        for name in ("DSDDMM_TUNER", "DSDDMM_TUNER_INTERVAL",
                     "DSDDMM_TUNER_LANE_FRAC", "DSDDMM_TUNER_SHADOW_N",
                     "DSDDMM_TUNER_BUDGET", "DSDDMM_TUNER_COOLDOWN",
                     "DSDDMM_TUNER_GAP", "DSDDMM_TUNER_TRIAL"):
            assert name in envreg.KNOBS
