"""Local-kernel-overlap fusion (``--fusion overlap``): the
double-buffered ring programs must be bit-identical to the sequential
path on every kernel mode of both shift strategies — the oracle the
structural HLO gate (tests/test_overlap_gate.py) complements."""

import numpy as np
import pytest

from distributed_sddmm_tpu.common import MatMode
from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
from distributed_sddmm_tpu.parallel.sparse_shift_15d import SparseShift15D
from distributed_sddmm_tpu.utils import oracle
from distributed_sddmm_tpu.utils.coo import HostCOO


def _S():
    return HostCOO.erdos_renyi(96, 80, 4, seed=3, values="normal")


def _pair(cls, S, unroll, **kw):
    seq = cls(S, R=16, unroll=unroll, **kw)
    ov = cls(S, R=16, unroll=unroll, overlap=True, **kw)
    assert ov.overlap and not seq.overlap
    return seq, ov


def _check_all_modes(seq, ov):
    """The four kernel modes + (dense) the fused pair, bitwise."""
    A = seq.dummy_initialize(MatMode.A)
    B = seq.dummy_initialize(MatMode.B)
    ones = seq.like_s_values(1.0)
    ones_t = seq.like_st_values(1.0)

    mid_seq = seq.sddmm_a(A, B, ones)
    mid_ov = ov.sddmm_a(A, B, ones)
    assert np.array_equal(np.asarray(mid_seq), np.asarray(mid_ov)), "sddmmA"
    midt_seq = seq.sddmm_b(A, B, ones_t)
    midt_ov = ov.sddmm_b(A, B, ones_t)
    assert np.array_equal(np.asarray(midt_seq), np.asarray(midt_ov)), "sddmmB"
    assert np.array_equal(
        np.asarray(seq.spmm_a(A, B, mid_seq)),
        np.asarray(ov.spmm_a(A, B, mid_seq)),
    ), "spmmA"
    assert np.array_equal(
        np.asarray(seq.spmm_b(A, B, midt_seq)),
        np.asarray(ov.spmm_b(A, B, midt_seq)),
    ), "spmmB"
    if isinstance(seq, DenseShift15D):
        o1, m1 = seq.fused_spmm(A, B, ones, MatMode.A)
        o2, m2 = ov.fused_spmm(A, B, ones, MatMode.A)
        assert np.array_equal(np.asarray(o1), np.asarray(o2)), "fused out"
        assert np.array_equal(np.asarray(m1), np.asarray(m2)), "fused mid"


# Rolled (unroll=False) rows beyond one representative per strategy
# are slow-marked: the rolled ring build is one code path whose
# overlap/bit-identity class each strategy keeps fast at its first
# config (plus test_rolled_overlap_als_end_to_end); every (c, fusion)
# combo keeps its fast UNROLLED row.
@pytest.mark.parametrize(
    "kw,unroll",
    [
        (dict(c=1, fusion_approach=2), True),
        # c2-f2 is covered by the c2-f1 row (replication axis live) plus
        # the c1-f2 rows (fusion-2 program shape) — slow-marked to fund
        # the PR 14 dist suites, like the rolled duplicates before it.
        pytest.param(dict(c=2, fusion_approach=2), True,
                     marks=pytest.mark.slow),
        (dict(c=2, fusion_approach=1), True),
        (dict(c=1, fusion_approach=2), False),
        pytest.param(dict(c=2, fusion_approach=2), False,
                     marks=pytest.mark.slow),
        pytest.param(dict(c=2, fusion_approach=1), False,
                     marks=pytest.mark.slow),
    ],
    ids=["c1-f2-unrolled", "c2-f2-unrolled", "c2-f1-unrolled",
         "c1-f2-rolled", "c2-f2-rolled", "c2-f1-rolled"],
)
def test_dense_shift_overlap_bit_identical(kw, unroll):
    S = _S()
    seq, ov = _pair(DenseShift15D, S, unroll, **kw)
    _check_all_modes(seq, ov)


@pytest.mark.parametrize(
    "c,unroll",
    [
        (1, True), (1, False),
        # c=2 unrolled duplicates the c=1 ring structure with the
        # replication axis the dense-shift c2 rows already pin —
        # slow-marked (PR 14) with its rolled sibling.
        pytest.param(2, True, marks=pytest.mark.slow),
        pytest.param(2, False, marks=pytest.mark.slow),
    ],
)
def test_sparse_shift_overlap_bit_identical(c, unroll):
    S = _S()
    seq, ov = _pair(SparseShift15D, S, unroll, c=c)
    _check_all_modes(seq, ov)


def test_overlap_matches_float64_oracle():
    """Not only self-consistent: the overlap fused pair agrees with the
    scipy/numpy ground truth like every other program."""
    S = _S()
    ov = DenseShift15D(S, R=16, c=2, fusion_approach=2, overlap=True)
    A = ov.dummy_initialize(MatMode.A)
    B = ov.dummy_initialize(MatMode.B)
    A_host = oracle.dummy_dense(ov.M_pad, ov.R)
    B_host = oracle.dummy_dense(ov.N_pad, ov.R)
    s_vals = ov.scatter_s_values(S.vals)
    out, mid = ov.fused_spmm(A, B, s_vals, MatMode.A)
    np.testing.assert_allclose(
        ov.gather_s_values(mid), oracle.sddmm(S, A_host, B_host), rtol=1e-4
    )
    np.testing.assert_allclose(
        ov.host_a(out)[: S.M], oracle.fused_spmm_a(S, A_host, B_host),
        rtol=1e-3, atol=1e-2,
    )


def test_overlap_comm_profile_matches_sequential():
    """Double buffering reorders hops, it must not change their count or
    volume — the trace report's comm-vs-costmodel agreement depends on
    the profile staying truthful for both builds."""
    S = _S()
    seq, ov = _pair(DenseShift15D, S, True, c=2, fusion_approach=2)
    for op in ("fusedSpMM", "sddmmA", "spmmA", "cgStep", "fusedSpMMB"):
        assert seq.comm_profile(op) == ov.comm_profile(op), op


def test_overlap_programs_cached_separately():
    """One strategy instance keys overlap and sequential variants apart
    (the program store inherits the distinction through the key)."""
    S = _S()
    ov = DenseShift15D(S, R=16, c=1, fusion_approach=2, overlap=True)
    ov._program("fused", use_st=False)
    assert any("overlap" in str(k) for k in ov._programs)
    seq = DenseShift15D(S, R=16, c=1, fusion_approach=2)
    seq._program("fused", use_st=False)
    assert any("seq" in str(k) for k in seq._programs)


def test_make_algorithm_overlap_gating():
    from distributed_sddmm_tpu.bench.harness import make_algorithm

    S = _S()
    alg = make_algorithm("15d_fusion2", S, 16, 1, overlap=True)
    assert alg.overlap
    alg = make_algorithm("15d_sparse", S, 16, 2, overlap=True)
    assert alg.overlap
    with pytest.raises(ValueError, match="overlap"):
        make_algorithm("25d_dense_replicate", S, 16, 1, overlap=True)


def test_cli_fusion_flag_reaches_record(tmp_path):
    """`--fusion overlap` flows through the CLI into the strategy build
    and the emitted record."""
    import json

    from distributed_sddmm_tpu.bench import cli

    out = tmp_path / "rec.jsonl"
    rc = cli.main([
        "er", "6", "4", "15d_fusion2", "16", "1",
        "--fusion", "overlap", "--trials", "1", "--warmup", "0",
        "--no-runstore", "-o", str(out),
    ])
    assert rc == 0
    rec = json.loads(out.read_text().splitlines()[0])
    assert rec["fusion"] == "overlap"
    assert rec["algorithm"] == "15d_fusion2"


def test_rolled_overlap_als_end_to_end():
    """The chained cgStep program over an overlap-built strategy (the
    combination the pod-scale path will run: rolled loops + overlap)
    converges identically to the sequential build."""
    from distributed_sddmm_tpu.models.als import DistributedALS

    S = HostCOO.erdos_renyi(64, 48, 5, seed=2, values="normal")

    def run(overlap):
        alg = DenseShift15D(S, R=8, c=1, fusion_approach=2, unroll=False,
                            overlap=overlap)
        m = DistributedALS(alg, S_host=S)
        m.run_cg(2, cg_iters=4)
        return np.asarray(m.A), np.asarray(m.B)

    A1, B1 = run(False)
    A2, B2 = run(True)
    assert np.array_equal(A1, A2) and np.array_equal(B1, B2)
