"""Buffer donation on the chained programs (PR 6 satellite): the CG-step
and GAT-layer programs must donate their carry buffers — pinned by
compiled-program inspection (``input_output_alias``), by bit-identical
results with donation on vs off, and by the automatic stand-down under
the resilience ladder's retry rung (a retry re-invokes the program with
buffers a donating first attempt already consumed)."""

import re

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_sddmm_tpu.common import MatMode
from distributed_sddmm_tpu.models.als import DistributedALS, donation_enabled
from distributed_sddmm_tpu.models.gat import GAT, GATLayer
from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
from distributed_sddmm_tpu.utils.coo import HostCOO


@pytest.fixture(autouse=True)
def _donation_on(monkeypatch):
    monkeypatch.delenv("DSDDMM_DONATE", raising=False)
    monkeypatch.delenv("DSDDMM_FAULTS", raising=False)
    monkeypatch.delenv("DSDDMM_GUARDS", raising=False)


def _aliased_params(hlo: str) -> list[int]:
    """Parameter indices aliased to outputs in the compiled module
    header: ``input_output_alias={ {0}: (0, {}, may-alias), ... }``."""
    line = next(l for l in hlo.splitlines() if "input_output_alias" in l)
    blob = line.split("input_output_alias=", 1)[1]
    return sorted(int(m) for m in re.findall(r"\((\d+), \{\}", blob))


def test_cg_step_donates_all_four_carries():
    S = HostCOO.erdos_renyi(64, 48, 5, seed=2, values="normal")
    alg = DenseShift15D(S, R=8, c=1, fusion_approach=2)
    m = DistributedALS(alg, S_host=S)
    m.initialize_embeddings()
    assert donation_enabled()
    prog = m._cg_iter_program(MatMode.A, m.ridge_lambda)
    X = m.A
    rsold = jnp.zeros(X.shape[:-1], jnp.float32)
    hlo = prog.lower(X, m.B, X, X, rsold).compile().as_text()
    # X (0), r (2), p (3), rsold (4) donate; `other` (1) must NOT.
    assert _aliased_params(hlo) == [0, 2, 3, 4]


def test_gat_square_layer_donates_activation_carry():
    S = HostCOO.erdos_renyi(64, 64, 5, seed=2, values="normal")
    layers = [GATLayer(input_features=8, features_per_head=4, num_heads=2)]
    gat = GAT(layers, DenseShift15D(S, R=8, c=1, fusion_approach=2))
    prog = gat._layer_program(0)
    X = gat.d_ops.dummy_initialize(MatMode.A)
    hlo = prog.lower(X, *layers[0].weights).compile().as_text()
    assert _aliased_params(hlo) == [0]


def test_gat_nonsquare_layer_skips_donation():
    """Donation is shape-gated: a layer whose output width differs from
    its input's could never reuse the buffer — requesting donation would
    only earn an unusable-donation warning."""
    S = HostCOO.erdos_renyi(64, 64, 5, seed=2, values="normal")
    layers = [GATLayer(input_features=8, features_per_head=8, num_heads=2)]
    gat = GAT(layers, DenseShift15D(S, R=8, c=1, fusion_approach=2))
    prog = gat._layer_program(0)
    gat.d_ops.set_r_value(layers[0].input_features)
    X = gat.d_ops.dummy_initialize(MatMode.A)
    hlo = prog.lower(X, *layers[0].weights).compile().as_text()
    assert not any("input_output_alias" in l for l in hlo.splitlines()[:1]) \
        or _aliased_params(hlo) == []


def test_run_cg_bit_identical_with_donation_on_and_off(monkeypatch):
    S = HostCOO.erdos_renyi(64, 48, 5, seed=2, values="normal")

    def run():
        alg = DenseShift15D(S, R=8, c=1, fusion_approach=2)
        m = DistributedALS(alg, S_host=S)
        m.run_cg(3, cg_iters=5)
        return np.asarray(m.A), np.asarray(m.B)

    monkeypatch.setenv("DSDDMM_DONATE", "1")
    A1, B1 = run()
    monkeypatch.setenv("DSDDMM_DONATE", "0")
    A0, B0 = run()
    assert np.array_equal(A1, A0)
    assert np.array_equal(B1, B0)


def test_donated_half_step_preserves_live_factors():
    """The half-step's entry X aliases the committed factor attribute;
    donation must never consume THAT buffer (the damped-restart ladder
    re-reads it). Pinned by using self.A after a donating half-step."""
    S = HostCOO.erdos_renyi(64, 48, 5, seed=2, values="normal")
    alg = DenseShift15D(S, R=8, c=1, fusion_approach=2)
    m = DistributedALS(alg, S_host=S)
    m.initialize_embeddings()
    A_before = np.asarray(m.A)  # host copy for comparison
    X = m._cg_run(MatMode.A, cg_max_iter=3, lam=m.ridge_lambda)
    # self.A's buffer must still be alive and unchanged (the half-step
    # did NOT commit).
    assert np.array_equal(np.asarray(m.A), A_before)
    assert np.asarray(X).shape == A_before.shape


def test_donation_stands_down_under_fault_plans():
    from distributed_sddmm_tpu.resilience import (
        FaultPlan, FaultSpec, fault_plan,
    )

    assert donation_enabled()
    with fault_plan(FaultPlan(
        [FaultSpec(site="output:cgStep", kind="nan", at=(2,))]
    )):
        assert not donation_enabled()
        # And the retry rung actually works: the injected NaN heals
        # without a donated-buffer RuntimeError.
        S = HostCOO.erdos_renyi(48, 32, 5, seed=2, values="normal")
        alg = DenseShift15D(S, R=8, c=1, fusion_approach=2)
        m = DistributedALS(alg, S_host=S)
        m.run_cg(2, cg_iters=3)
        assert np.isfinite(np.asarray(m.A)).all()
    assert donation_enabled()


def test_donation_kill_switch(monkeypatch):
    monkeypatch.setenv("DSDDMM_DONATE", "0")
    assert not donation_enabled()
    S = HostCOO.erdos_renyi(48, 32, 5, seed=2, values="normal")
    alg = DenseShift15D(S, R=8, c=1, fusion_approach=2)
    m = DistributedALS(alg, S_host=S)
    m.initialize_embeddings()
    prog = m._cg_iter_program(MatMode.A, m.ridge_lambda)
    X = m.A
    rsold = jnp.zeros(X.shape[:-1], jnp.float32)
    hlo = prog.lower(X, m.B, X, X, rsold).compile().as_text()
    header = hlo.splitlines()[0]
    assert "input_output_alias" not in header
