"""Structural HLO gate for the multi-host program path (tier-1
acceptance, ``test_codegen_gate.py`` style) + the pod warm-start
contract.

**The gate**: the fused SDDMM→SpMM pair, AOT-compiled for a REAL 2-host
v5e topology (``jax.experimental.topologies``, no chips needed), must
contain collectives whose replica groups SPAN THE HOST BOUNDARY — the
structural proof the compiled program is one global multi-host program,
not p copies of a local one. With ``c=2`` the layout math says the
replication axis (all-gather + reduce-scatter) crosses hosts while the
rows ring stays intra-host; the gate asserts the boundary landed
exactly there. The committed ``MULTIHOST_HLO.json`` is this probe's
banked record.

**The warm start**: a pod worker's programs key through the ProgramStore
under the ``dN.pK`` dist segment, and a worker process restarting on
the same slot must warm from the shared disk store with ZERO live
compiles — while an unlabeled (single-controller) process of the same
problem must MISS those entries (per-slot executables must never
alias). Exercised with two real OS processes against one store.

Subprocess + ``TPU_SKIP_MDS_QUERY=1`` for the same libtpu metadata
reason as the other gates.
"""

import json
import os
import pathlib
import subprocess
import sys

from distributed_sddmm_tpu.dist.hlo import scan_cross_host

REPO = pathlib.Path(__file__).resolve().parents[1]

_PROBE = """
import json, sys
sys.path.insert(0, {repo!r})
from distributed_sddmm_tpu.utils.platform import force_cpu_platform
force_cpu_platform(n_devices=8, replace=True)
from distributed_sddmm_tpu.dist.hlo import multihost_hlo_report
print("RESULT " + json.dumps(multihost_hlo_report()))
"""


def test_multihost_fused_pair_v5e_hlo_gate():
    env = dict(os.environ)
    env.update({
        "TPU_SKIP_MDS_QUERY": "1",
        "DSDDMM_PROGRAMS": "0",
        "DSDDMM_RUNSTORE": "0",
        "PYTHONPATH": str(REPO),
    })
    proc = subprocess.run(
        [sys.executable, "-c", _PROBE.format(repo=str(REPO))],
        capture_output=True, text=True, timeout=540, env=env,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    line = [l for l in proc.stdout.splitlines() if l.startswith("RESULT ")]
    assert line, proc.stdout[-2000:]
    rec = json.loads(line[0][len("RESULT "):])
    assert rec["topology"] == "v5e:2x4" and rec["n_hosts"] == 2
    assert rec["is_scheduled"] is True
    # The acceptance bar: >= 1 collective whose replica groups span
    # both hosts, with no collective the scanner could not read.
    assert rec["cross_host_collectives"] >= 1, rec
    assert rec["unparsed_group_lines"] == 0, rec
    # The boundary landed where the 1.5D layout math puts it at c=2:
    # replication (all-gather + reduce-scatter) crosses hosts, the
    # rows ring stays on intra-host ICI.
    assert rec["axis_spans_hosts"] == {
        "rows": False, "cols": True, "layers": False,
    }
    assert rec["collectives"]["all-gather"]["cross_host"] >= 1
    assert rec["collectives"]["reduce-scatter"]["cross_host"] >= 1
    assert rec["collectives"]["collective-permute"]["cross_host"] == 0
    # Matches the committed banked record on every structural field.
    committed = json.loads((REPO / "MULTIHOST_HLO.json").read_text())
    for field in ("topology", "p", "c", "n_hosts", "device_processes",
                  "axis_spans_hosts", "cross_host_collectives",
                  "collectives"):
        assert rec[field] == committed[field], (field, rec, committed)


# --------------------------------------------------------------------- #
# The scanner's own contract on synthetic HLO
# --------------------------------------------------------------------- #

_HLO_CROSS = """\
HloModule jit_prog, is_scheduled=true

%body (arg: f32[8]) -> f32[8] {
  %ag = f32[8] all-gather(f32[4] %x), replica_groups={{0,1},{2,3}}, channel_id=1
  %cp = f32[8] collective-permute(f32[8] %y), source_target_pairs={{0,2},{2,0},{1,3},{3,1}}
  ROOT %r = f32[8] add(%ag, %cp)
}
"""

_HLO_IOTA = """\
HloModule jit_prog, is_scheduled=true

%body (arg: f32[8]) -> f32[8] {
  ROOT %ag = f32[8] all-gather(f32[4] %x), replica_groups=[2,2]<=[4], channel_id=1
}
"""


def test_scanner_classifies_cross_host_groups():
    # Hosts: partitions 0,1 on host 0; partitions 2,3 on host 1.
    procs = [0, 0, 1, 1]
    scan = scan_cross_host(_HLO_CROSS, procs)
    assert scan["per_op"]["all-gather"] == {
        "count": 1, "cross_host": 0, "groups": [[0, 1], [2, 3]],
    }
    # Every permute pair hops between hosts.
    assert scan["per_op"]["collective-permute"]["cross_host"] == 1
    assert scan["cross_host_collectives"] == 1
    # Flip the host map: the all-gather pairs now straddle.
    scan = scan_cross_host(_HLO_CROSS, [0, 1, 0, 1])
    assert scan["per_op"]["all-gather"]["cross_host"] == 1
    assert scan["per_op"]["collective-permute"]["cross_host"] == 0
    assert scan["cross_host_collectives"] == 1


def test_scanner_treats_empty_groups_as_all_participants():
    # replica_groups={} is HLO's implicit one-group-of-ALL form (a
    # global all-reduce): on a 2-host map it spans hosts.
    hlo = (
        "HloModule jit_prog, is_scheduled=true\n"
        "  %ar = f32[8] all-reduce(f32[8] %x), replica_groups={}, "
        "channel_id=1\n"
    )
    scan = scan_cross_host(hlo, [0, 0, 1, 1])
    assert scan["per_op"]["all-reduce"]["cross_host"] == 1
    assert scan["cross_host_collectives"] == 1
    # Single-host map: same form, no boundary to cross.
    assert scan_cross_host(hlo, [0, 0])["cross_host_collectives"] == 0


def test_scanner_reports_unparsed_iota_groups():
    scan = scan_cross_host(_HLO_IOTA, [0, 0, 1, 1])
    assert scan["unparsed_group_lines"] == 1
    assert scan["cross_host_collectives"] == 0


def test_scanner_empty_hlo():
    scan = scan_cross_host("", [0, 1])
    assert scan["cross_host_collectives"] == 0
    assert scan["per_op"] == {}


# --------------------------------------------------------------------- #
# Pod warm start: dist-keyed ProgramStore round trip, two OS processes
# --------------------------------------------------------------------- #

_WARM_WORKER = """
import json, sys
sys.path.insert(0, {repo!r})
from distributed_sddmm_tpu.utils.platform import force_cpu_platform
force_cpu_platform(n_devices=8, replace=True)
import numpy as np
from distributed_sddmm_tpu import programs
from distributed_sddmm_tpu.common import MatMode
from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
from distributed_sddmm_tpu.utils.coo import HostCOO

store = programs.ProgramStore({store!r})
S = HostCOO.erdos_renyi(48, 40, 4, seed=2, values="normal")
alg = DenseShift15D(S, R=8, c=2, fusion_approach=2)
assert programs.bind_strategy(alg, "podfp", store=store)
A = alg.dummy_initialize(MatMode.A)
B = alg.dummy_initialize(MatMode.B)
out, _mid = alg.fused_spmm(A, B, alg.like_s_values(1.0))
fp = float(np.sum(np.asarray(out, np.float64) ** 2))
print("RESULT " + json.dumps(
    dict(stats=store.stats(), fp=fp,
         keys=[r["key"] for r in store.index()])))
"""


def _run_warm_worker(store, nprocs=None, proc_id=None):
    env = dict(os.environ)
    env.update({"DSDDMM_PROGRAMS": "0", "DSDDMM_RUNSTORE": "0",
                "PYTHONPATH": str(REPO)})
    for k in ("DSDDMM_DIST_NPROCS", "DSDDMM_DIST_PROC_ID"):
        env.pop(k, None)
    if nprocs is not None:
        env["DSDDMM_DIST_NPROCS"] = str(nprocs)
        env["DSDDMM_DIST_PROC_ID"] = str(proc_id)
    proc = subprocess.run(
        [sys.executable, "-c",
         _WARM_WORKER.format(repo=str(REPO), store=str(store))],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert proc.returncode == 0, proc.stdout[-1500:] + proc.stderr[-1500:]
    line = [l for l in proc.stdout.splitlines() if l.startswith("RESULT ")]
    return json.loads(line[0][len("RESULT "):])


def test_pod_worker_warm_start_zero_live_compiles(tmp_path):
    store = tmp_path / "programs"
    cold = _run_warm_worker(store, nprocs=2, proc_id=0)
    assert cold["stats"]["live_compiles"] > 0
    assert cold["stats"]["hits"] == 0
    assert cold["keys"] and all(k.endswith(":d2.p0") for k in cold["keys"])

    # The same pod slot restarting: warm start, ZERO live compiles,
    # bit-identical output.
    warm = _run_warm_worker(store, nprocs=2, proc_id=0)
    assert warm["stats"]["live_compiles"] == 0, warm
    assert warm["stats"]["hits"] >= 1
    assert warm["fp"] == cold["fp"]

    # An unlabeled single-controller process must MISS the pod-keyed
    # entries (compiles live under its own 6-segment keys) — per-slot
    # executables never alias across pod shapes.
    solo = _run_warm_worker(store)
    assert solo["stats"]["live_compiles"] > 0
    assert set(solo["keys"]) > set(cold["keys"])  # both generations present
    assert all(
        not k.endswith(":d2.p0")
        for k in set(solo["keys"]) - set(cold["keys"])
    )
    assert solo["fp"] == cold["fp"]
