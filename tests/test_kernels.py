import numpy as np
import jax.numpy as jnp

from distributed_sddmm_tpu.ops.kernels import XlaKernel, get_kernel
from distributed_sddmm_tpu.utils import oracle
from distributed_sddmm_tpu.utils.coo import HostCOO


def _tile(S: HostCOO, max_nnz: int):
    """Pad a host COO into the static-shape tile convention."""
    pad = max_nnz - S.nnz
    rows = np.concatenate([S.rows, np.zeros(pad, np.int64)]).astype(np.int32)
    cols = np.concatenate([S.cols, np.zeros(pad, np.int64)]).astype(np.int32)
    vals = np.concatenate([S.vals, np.zeros(pad)]).astype(np.float32)
    return jnp.array(rows), jnp.array(cols), jnp.array(vals)


def _setup(M=32, N=24, R=8, seed=0):
    S = HostCOO.erdos_renyi(M, N, 4, seed=seed, values="normal")
    rng = np.random.default_rng(seed + 1)
    A = rng.standard_normal((M, R)).astype(np.float32)
    B = rng.standard_normal((N, R)).astype(np.float32)
    return S, A, B


def test_get_kernel():
    assert isinstance(get_kernel("xla"), XlaKernel)


def test_sddmm_padded_matches_oracle():
    S, A, B = _setup()
    rows, cols, vals = _tile(S, S.nnz + 17)
    out = XlaKernel().sddmm(rows, cols, vals, jnp.array(A), jnp.array(B))
    expected = oracle.sddmm(S, A.astype(np.float64), B.astype(np.float64))
    np.testing.assert_allclose(np.asarray(out[: S.nnz]), expected, rtol=1e-4, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(out[S.nnz :]), 0.0)


def test_spmm_padded_matches_oracle():
    S, A, B = _setup()
    rows, cols, vals = _tile(S, S.nnz + 9)
    out = XlaKernel().spmm(rows, cols, vals, jnp.array(B), out_rows=S.M)
    expected = oracle.spmm_a(S, B.astype(np.float64))
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-4, atol=1e-5)


def test_spmm_transpose_via_swap():
    """SpMM-B is SpMM over the transposed tile (rows/cols swapped)."""
    S, A, B = _setup()
    rows, cols, vals = _tile(S, S.nnz)
    out = XlaKernel().spmm(cols, rows, vals, jnp.array(A), out_rows=S.N)
    expected = oracle.spmm_b(S, A.astype(np.float64))
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-4, atol=1e-5)


def test_chunked_paths_match_single_pass(monkeypatch):
    """Past XLA_GATHER_BUDGET both ops fall back to sequential nnz
    segments (the reference grid's heavy corner would otherwise need an
    nnz*R gather larger than HBM); the segmented results must be
    bit-compatible with the one-pass path, including a ragged final
    segment and inert padding."""
    from distributed_sddmm_tpu.ops import kernels as K

    S, A, B = _setup(M=48, N=40, R=8, seed=3)
    rows, cols, vals = _tile(S, S.nnz + 5)  # nnz+5 not divisible by seg
    k = XlaKernel()
    one_sddmm = k.sddmm(rows, cols, vals, jnp.array(A), jnp.array(B))
    one_spmm = k.spmm(rows, cols, vals, jnp.array(B), out_rows=S.M)
    # 7*R elements per segment: forces many segments plus a ragged tail.
    monkeypatch.setattr(K, "XLA_GATHER_BUDGET", 7 * A.shape[1])
    chunked_sddmm = k.sddmm(rows, cols, vals, jnp.array(A), jnp.array(B))
    chunked_spmm = k.spmm(rows, cols, vals, jnp.array(B), out_rows=S.M)
    np.testing.assert_allclose(
        np.asarray(chunked_sddmm), np.asarray(one_sddmm), rtol=1e-5,
        atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(chunked_spmm), np.asarray(one_spmm), rtol=1e-5, atol=1e-6)
