"""End-to-end real-graph workflow: generate -> mtx write -> permute ->
file-bench -> chart render (reference `bench_file.cpp` +
`random_permute.cpp:42-57` + the notebook pipeline, run here as one chain).
"""

import json
import pathlib

import pytest

from distributed_sddmm_tpu.bench.cli import main as bench_main
from distributed_sddmm_tpu.utils.coo import HostCOO


def test_chain(tmp_path: pathlib.Path):
    mtx = tmp_path / "g.mtx"
    S = HostCOO.rmat(log_m=8, edge_factor=6, seed=3)
    S.save_mtx(str(mtx))

    # permute (load-balance preprocessing)
    permuted = tmp_path / "g-permuted.mtx"
    assert bench_main(["permute", str(mtx), "--seed", "1", "-o", str(permuted)]) == 0
    Sp = HostCOO.load_mtx(str(permuted))
    assert (Sp.M, Sp.N, Sp.nnz) == (S.M, S.N, S.nnz)

    # file bench with breakdown on one 1.5D and one 2.5D algorithm
    records = tmp_path / "records.jsonl"
    for alg in ("15d_fusion2", "25d_sparse_replicate"):
        rc = bench_main([
            "file", str(permuted), alg, "16", "2",
            "--kernel", "xla", "--trials", "1", "--breakdown",
            "-o", str(records),
        ])
        assert rc == 0

    recs = [json.loads(l) for l in records.read_text().splitlines()]
    assert len(recs) == 2
    for rec in recs:
        assert rec["overall_throughput"] > 0
        for key in ("replication", "ppermute"):
            assert key in rec["perf_stats"]

    # chart render consumes the records
    matplotlib = pytest.importorskip("matplotlib")  # noqa: F841
    from distributed_sddmm_tpu.tools.charts import main as charts_main

    out = tmp_path / "charts"
    assert charts_main([str(records), "-o", str(out)]) == 0
    assert (out / "benchmark.png").exists()
    assert (out / "winners.json").exists()
