"""Tier-1 codegen smoke: scripts/codegen_smoke.py in a subprocess.

Pins the PR-9 acceptance surface end to end: variant selection from a
plan, ProgramStore round-trip with variant-id keys (warm hit, generic
no-alias, stale-entry evict-and-recompile), >= 2x padded-lane-waste
reduction with bit-identical results on a skewed CPU-interpreted
problem, and the bench record's ``kernel_variant`` +
``padded_lane_frac`` fields.
"""

import json
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]


def test_codegen_smoke(tmp_path):
    out = tmp_path / "report.json"
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "codegen_smoke.py"),
         "-o", str(out)],
        capture_output=True, text=True, timeout=540,
        env={"PATH": "/usr/bin:/bin:/usr/local/bin", "HOME": "/tmp",
             "JAX_PLATFORMS": "cpu", "DSDDMM_RUNSTORE": "0",
             "DSDDMM_PROGRAMS": "0"},
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    rep = json.loads(out.read_text())

    # Selection: the variant registered as a candidate and the cost
    # model discounts it on the skewed problem.
    assert rep["selection"]["variant_candidates"] >= 1
    assert rep["selection"]["cost_factor"] < 1.0

    # Acceptance: >= 2x counted padded-lane-waste reduction with
    # bit-identical results.
    assert rep["waste"]["reduction_ratio"] >= 2.0
    assert rep["waste"]["bit_identical"] is True

    # Store: warm start hits, generic plan never aliases, stale entry
    # evicted and recompiled.
    assert rep["store"]["cold"]["live_compiles"] >= 1
    assert rep["store"]["warm"]["hits"] >= 1
    assert rep["store"]["warm"]["live_compiles"] == 0
    assert rep["store"]["generic"]["live_compiles"] >= 1
    assert rep["store"]["evicted"]["live_compiles"] >= 1
    assert rep["store"]["variant_keys"] >= 1

    # Records carry the variant id and the counted pad metric.
    assert rep["record"]["kernel_variant"].startswith("v1.")
    assert 0.0 <= rep["record"]["padded_lane_frac"] < 1.0
    assert rep["counters"]["codegen_variants_built"] >= 1
