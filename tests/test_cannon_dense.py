import numpy as np
import pytest

from distributed_sddmm_tpu.common import KernelMode, MatMode
from distributed_sddmm_tpu.parallel.cannon_dense_25d import CannonDense25D
from distributed_sddmm_tpu.utils import oracle
from distributed_sddmm_tpu.utils.coo import HostCOO


def _problem(M=64, N=48, seed=0):
    return HostCOO.erdos_renyi(M, N, 4, seed=seed, values="normal")


def _dense_inputs(alg):
    A = alg.dummy_initialize(MatMode.A)
    B = alg.dummy_initialize(MatMode.B)
    A_host = oracle.dummy_dense(alg.M_pad, alg.R)
    B_host = oracle.dummy_dense(alg.N_pad, alg.R)
    return A, B, A_host, B_host


# (c,) configs on 8 devices: c=2 -> 2x2x2; c=8 -> 1x1x8.
CONFIGS = [2, 8]


def test_grid_requirements():
    S = _problem()
    with pytest.raises(ValueError):
        CannonDense25D(S, R=8, c=1)  # p/c=8 not a perfect square
    with pytest.raises(ValueError):
        CannonDense25D(S, R=7, c=2)  # sqrt(p/c)=2 does not divide 7


def test_skew_roundtrip():
    S = _problem()
    alg = CannonDense25D(S, R=8, c=2)
    A, B, A_host, _ = _dense_inputs(alg)
    A_sk, _ = alg.initial_shift(A, None, KernelMode.SDDMM_A)
    A_rt, _ = alg.de_shift(A_sk, None, KernelMode.SDDMM_A)
    np.testing.assert_allclose(alg.host_a(A_rt), A_host[: alg.M], rtol=1e-6)
    # B-mode skews B, leaves A untouched
    _, B_sk = alg.initial_shift(None, B, KernelMode.SPMM_B)
    _, B_rt = alg.de_shift(None, B_sk, KernelMode.SPMM_B)
    np.testing.assert_allclose(alg.host_b(B_rt), oracle.dummy_dense(alg.N_pad, 8)[: alg.N], rtol=1e-6)


@pytest.mark.parametrize("c", CONFIGS)
def test_sddmm_a(c):
    S = _problem()
    alg = CannonDense25D(S, R=8, c=c)
    A, B, A_host, B_host = _dense_inputs(alg)
    A_sk, _ = alg.initial_shift(A, None, KernelMode.SDDMM_A)
    sv = alg.scatter_s_values(S.transpose().vals)  # A-ops: S^T value order
    out = alg.sddmm_a(A_sk, B, sv)
    expected = oracle.sddmm(S.transpose(), B_host, A_host)
    np.testing.assert_allclose(alg.gather_s_values(out), expected, rtol=1e-4)


@pytest.mark.parametrize("c", CONFIGS)
def test_sddmm_b(c):
    S = _problem()
    alg = CannonDense25D(S, R=8, c=c)
    A, B, A_host, B_host = _dense_inputs(alg)
    _, B_sk = alg.initial_shift(None, B, KernelMode.SDDMM_B)
    sv = alg.scatter_st_values(S.vals)  # B-ops: S value order
    out = alg.sddmm_b(A, B_sk, sv)
    expected = oracle.sddmm(S, A_host, B_host)
    np.testing.assert_allclose(alg.gather_st_values(out), expected, rtol=1e-4)


@pytest.mark.parametrize("c", CONFIGS)
def test_spmm_a(c):
    S = _problem()
    alg = CannonDense25D(S, R=8, c=c)
    A, B, A_host, B_host = _dense_inputs(alg)
    sv = alg.scatter_s_values(S.transpose().vals)
    out = alg.spmm_a(alg.like_a_matrix(0.0), B, sv)
    out, _ = alg.de_shift(out, None, KernelMode.SPMM_A)
    np.testing.assert_allclose(
        alg.host_a(out)[: S.M], oracle.spmm_a(S, B_host), rtol=1e-4, atol=1e-3
    )


@pytest.mark.parametrize("c", CONFIGS)
def test_spmm_b(c):
    S = _problem()
    alg = CannonDense25D(S, R=8, c=c)
    A, B, A_host, B_host = _dense_inputs(alg)
    sv = alg.scatter_st_values(S.vals)
    out = alg.spmm_b(A, alg.like_b_matrix(0.0), sv)
    _, out = alg.de_shift(None, out, KernelMode.SPMM_B)
    np.testing.assert_allclose(
        alg.host_b(out)[: S.N], oracle.spmm_b(S, A_host), rtol=1e-4, atol=1e-3
    )


def test_spmm_accumulates_into_moving_buffer():
    """The rotating output accumulates on top of its initial content
    (reference `beta=1` semantics through the rotating bBuf)."""
    S = _problem()
    alg = CannonDense25D(S, R=8, c=2)
    A, B, A_host, B_host = _dense_inputs(alg)
    base, _ = alg.initial_shift(A, None, KernelMode.SPMM_A)
    out = alg.spmm_a(base, B, alg.scatter_s_values(S.transpose().vals))
    out, _ = alg.de_shift(out, None, KernelMode.SPMM_A)
    np.testing.assert_allclose(
        alg.host_a(out)[: S.M],
        A_host[: S.M] + oracle.spmm_a(S, B_host),
        rtol=1e-4, atol=1e-3,
    )


def test_fused_and_fingerprint_parity_with_15d():
    from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D

    S = _problem()
    alg = CannonDense25D(S, R=8, c=2)
    A, B, A_host, B_host = _dense_inputs(alg)
    A_sk, _ = alg.initial_shift(A, None, KernelMode.SDDMM_A)
    out, mid = alg.fused_spmm(A_sk, B, alg.scatter_s_values(S.transpose().vals))
    out, _ = alg.de_shift(out, None, KernelMode.SPMM_A)
    expected = oracle.fused_spmm_a(S, A_host, B_host)
    np.testing.assert_allclose(alg.host_a(out)[: S.M], expected, rtol=1e-3, atol=1e-2)

    ref = DenseShift15D(S, R=8, c=2)
    A2 = ref.dummy_initialize(MatMode.A)
    B2 = ref.dummy_initialize(MatMode.B)
    out2, _ = ref.fused_spmm(A2, B2, ref.scatter_s_values(S.vals))
    fp1 = alg.fingerprint(alg.host_a(out)[: S.M])
    fp2 = ref.fingerprint(ref.host_a(out2)[: S.M])
    np.testing.assert_allclose(fp1, fp2, rtol=1e-5)


def test_rolled_matches_unrolled():
    S = _problem()
    res = []
    for unroll in (True, False):
        alg = CannonDense25D(S, R=8, c=2, unroll=unroll)
        A, B, _, _ = _dense_inputs(alg)
        _, B_sk = alg.initial_shift(None, B, KernelMode.SDDMM_B)
        out = alg.sddmm_b(A, B_sk, alg.scatter_st_values(S.vals))
        res.append(alg.gather_st_values(out))
    np.testing.assert_allclose(res[0], res[1], rtol=1e-5)
