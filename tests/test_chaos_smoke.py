"""Tier-1 smoke for gray-failure hardening (ISSUE 17 acceptance).

Runs ``scripts/chaos_smoke.py`` as a subprocess — ``bench fleet`` under
a seeded four-fault chaos schedule (wedge, partition, corrupt, kill):
every gray fault must be detected within the deadline (breaker open for
wedge/partition, byzantine quarantine for corrupt), every delivered
reply must stay bit-identical to the single-engine oracle even while a
replica answers plausible wrong bytes, the kill must heal warm, and the
recorded chaos events must replay the locally re-derived seeded
timeline. Exit contract 0 (all green) / 2 (any check red).
"""

import json
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]
SCRIPT = REPO / "scripts" / "chaos_smoke.py"


def test_chaos_smoke_script(tmp_path):
    out = tmp_path / "chaos_smoke.json"
    proc = subprocess.run(
        [sys.executable, str(SCRIPT), "-o", str(out)],
        capture_output=True, text=True, timeout=600, cwd=REPO,
        env={
            "PATH": "/usr/bin:/bin",
            "HOME": "/tmp",
            "JAX_PLATFORMS": "cpu",
            "DSDDMM_RUNSTORE": "0",
            "DSDDMM_PROGRAMS": "0",
        },
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(out.read_text())
    assert report["ok"] is True
    (drill,) = report["checks"]

    assert drill["exit_code"] == 0
    # The detector fired AND the client never saw the corruption: wrong
    # bytes were caught by the pre-delivery audit, arbitrated away, and
    # the liar quarantined.
    assert drill["mismatches"] == 0
    assert drill["audit_mismatches"] > 0
    assert drill["quarantines"] >= 1
    assert drill["lost"] == 0
    # Every injected gray fault detected within the deadline.
    assert drill["detection_ok"] is True
    assert {d["kind"] for d in drill["detection"]} == {
        "wedge", "partition", "corrupt"}
    assert all(d["detected"] for d in drill["detection"])
    assert drill["breaker_opens"] >= 2  # wedge + partition victims
    # The crash fault healed warm, availability held.
    assert drill["killed"]
    assert drill["replacement_live_compiles"] == 0
    assert drill["availability"] >= 0.9
    # Same seed, same timeline: the run replayed the local derivation.
    assert drill["timeline_ok"] is True
    # The zero-tolerance gate axes are derived from the record.
    assert "fleet:audit_mismatch" in drill["gate_axes"]
    # PR-19: fleet-wide tracing rode along under the full four-fault
    # schedule — every delivered reply reconstructs one complete
    # cross-process chain (winning span within 1 ms of the router's
    # recorded latency), and report-trace held its 0/2 exit contract.
    assert drill["trace_ok"] is True
    assert drill["trace_coverage"] == 1.0
    assert drill["trace_delivered"] > 0
    assert drill["trace_shards"] >= 3
    assert drill["trace_fleet_links"] > 0
    assert drill["report_trace_exit"] == 0
    assert drill["report_trace_bad_exit"] == 2
    assert "fleet:trace_coverage" in drill["gate_axes"]


def test_exit_code_contract():
    """The 0/2 contract without a second subprocess run."""
    sys.path.insert(0, str(REPO / "scripts"))
    try:
        import chaos_smoke
    finally:
        sys.path.pop(0)
    assert chaos_smoke.exit_code({"ok": True}) == 0
    assert chaos_smoke.exit_code({"ok": False}) == 2
    assert chaos_smoke.exit_code({}) == 2
