"""Regression verdict logic + the compare/gate/report CLI contract.

Synthetic run pairs pin the four verdict regimes (clear regression,
within-noise, improvement, missing phase), the gate's exit-code
contract (0/2/3 — the interface CI scripts key on), the rendered
compare table's comm/FLOP attribution columns, the report-trace
validator's nonzero exit on schema violations, and the HTML dashboard.
All CPU-only and free of benchmark execution: documents are built
directly, exactly what the store would hold.
"""

import json

import pytest

from distributed_sddmm_tpu.bench import cli
from distributed_sddmm_tpu.obs import regress
from distributed_sddmm_tpu.obs.store import RunStore


def make_doc(run_id, scale=1.0, key="key-a", phases=("fusedSpMM",),
             overhead_s=0.0, comm_words=1000.0):
    """One synthetic run doc; ``scale`` multiplies every phase's time."""
    metrics = {}
    for ph in phases:
        metrics[ph] = {
            "calls": 10, "kernel_s": 0.050 * scale, "overhead_s": overhead_s,
            "retries": 0, "comm_words": comm_words,
            "comm_words_extra": 0.0, "flops": 2.0e8,
        }
    return {
        "run_id": run_id, "key": key, "backend": "cpu", "code_hash": "c0de",
        "record": {
            "algorithm": "15d_fusion2", "app": "vanilla", "R": 64, "c": 2,
            "fused": True, "elapsed": 0.05 * scale,
            "overall_throughput": 4.0 / scale, "metrics": metrics,
        },
    }


class TestVerdicts:
    def test_clear_regression(self):
        rep = regress.compare(make_doc("b", 2.0), doc_a=make_doc("a", 1.0))
        assert rep["verdict"] == "regression"
        assert rep["regressions"] == ["fusedSpMM"]
        row = rep["phases"]["fusedSpMM"]
        assert row["verdict"] == "regression"
        assert row["delta_pct"] == pytest.approx(100.0)
        assert row["attribution"] == "compute"

    def test_within_noise(self):
        rep = regress.compare(make_doc("b", 1.05), doc_a=make_doc("a", 1.0))
        assert rep["verdict"] == "ok"
        assert rep["phases"]["fusedSpMM"]["verdict"] == "ok"
        assert not rep["regressions"]

    def test_improvement(self):
        rep = regress.compare(make_doc("b", 0.5), doc_a=make_doc("a", 1.0))
        assert rep["verdict"] == "improvement"
        assert rep["improvements"] == ["fusedSpMM"]

    def test_missing_phase_is_a_regression_verdict(self):
        a = make_doc("a", 1.0, phases=("fusedSpMM", "cgStep"))
        b = make_doc("b", 1.0, phases=("fusedSpMM",))
        rep = regress.compare(b, doc_a=a)
        assert rep["missing"] == ["cgStep"]
        assert rep["verdict"] == "regression"  # a vanished phase gates

    def test_new_phase_is_not_a_regression(self):
        a = make_doc("a", 1.0, phases=("fusedSpMM",))
        b = make_doc("b", 1.0, phases=("fusedSpMM", "cgStep"))
        rep = regress.compare(b, doc_a=a)
        assert rep["new"] == ["cgStep"]
        assert rep["verdict"] == "ok"

    def test_overhead_attribution(self):
        """A slowdown living in retry/fault overhead blames overhead,
        not compute."""
        a = make_doc("a", 1.0)
        b = make_doc("b", 1.0, overhead_s=1.0)  # kernel unchanged
        rep = regress.compare(b, doc_a=a)
        row = rep["phases"]["fusedSpMM"]
        assert row["verdict"] == "regression"
        assert row["attribution"] == "overhead"

    def test_comm_attribution(self):
        """Kernel slower AND counted volume moved → blame comm."""
        a = make_doc("a", 1.0, comm_words=1000.0)
        b = make_doc("b", 2.0, comm_words=2000.0)
        rep = regress.compare(b, doc_a=a)
        assert rep["phases"]["fusedSpMM"]["attribution"] == "comm"

    def test_rolling_baseline_median_absorbs_one_outlier(self):
        """One slow baseline run must not drag the band: median-of-reps,
        not last-run diffing."""
        baseline = [make_doc(f"b{i}", s)
                    for i, s in enumerate([1.0, 1.02, 5.0, 0.98, 1.01])]
        rep = regress.compare(make_doc("new", 1.04), baseline_docs=baseline)
        assert rep["verdict"] == "ok"
        rep = regress.compare(make_doc("new", 2.0), baseline_docs=baseline)
        assert rep["verdict"] == "regression"

    def test_key_mismatch_flagged_not_fatal(self):
        rep = regress.compare(
            make_doc("b", 1.0, key="key-b"), doc_a=make_doc("a", 1.0)
        )
        assert rep["comparable"] is False

    def test_phase_stats_metrics_namespace_with_trace_enrichment(self):
        """Rows come from record metrics (the namespace every run has);
        the trace aggregate only donates the model column — so traced
        and untraced runs never disagree on which phases exist."""
        doc = make_doc("a", 1.0)
        doc["phases"] = {
            "fusedSpMM": {"calls": 4, "total_s": 2.0, "kernel_s": 1.8,
                          "overhead_s": 0.2, "retries": 1,
                          "comm_words": 50.0, "flops": 1e6, "pairs": 4.0,
                          "model_words": 500.0},
            "als:step": {"calls": 2, "total_s": 1.0, "kernel_s": 1.0,
                         "overhead_s": 0.0, "retries": 0,
                         "comm_words": 0.0, "flops": 0.0},
        }
        st = regress.phase_stats(doc)
        assert "als:step" not in st  # app spans stay out of the verdict set
        row = st["fusedSpMM"]
        assert row["calls"] == 10          # metrics, not the trace's 4
        assert row["t_call"] == pytest.approx(0.005)
        # counted words from metrics vs modeled words from the trace
        assert row["model_ratio"] == pytest.approx(1000.0 / 500.0)

    def test_traced_vs_untraced_docs_compare_cleanly(self):
        """A doc with a trace aggregate judged against one without must
        not produce spurious 'missing' phases (verdict-source skew)."""
        a = make_doc("a", 1.0)
        a["phases"] = {
            "als:step": {"calls": 2, "total_s": 1.0, "kernel_s": 1.0,
                         "overhead_s": 0.0, "retries": 0,
                         "comm_words": 0.0, "flops": 0.0},
        }
        rep = regress.compare(make_doc("b", 1.0), doc_a=a)
        assert rep["verdict"] == "ok"
        assert not rep["missing"] and not rep["new"]


def make_fleet_doc(run_id, *, availability=1.0, audit_mismatches=0,
                   hedges=0, hedge_wins=0):
    doc = make_doc(run_id)
    doc["record"]["fleet"] = {
        "offered": 100, "availability": availability,
        "audit_mismatches": audit_mismatches,
        "hedges": hedges, "hedge_wins": hedge_wins,
    }
    return doc


class TestFleetAxes:
    """PR-17 gate axes: ``fleet:audit_mismatch`` is ZERO-tolerance
    (replies are bit-identical by construction — one cross-replica
    mismatch is a byzantine event, not noise), ``fleet:hedge_win_rate``
    is a banded optional axis."""

    def test_audit_mismatch_axis_is_hard(self):
        a = make_fleet_doc("a")
        b = make_fleet_doc("b", audit_mismatches=1)
        rep = regress.compare(b, doc_a=a)
        assert rep["verdict"] == "regression"
        assert "fleet:audit_mismatch" in rep["regressions"]
        row = rep["phases"]["fleet:audit_mismatch"]
        assert row["hard_axis"] is True
        assert row["attribution"] == "fleet"

    def test_audit_mismatch_regresses_even_without_baseline(self):
        """No baseline band to hide in: a brand-new axis with a nonzero
        count still gates."""
        rep = regress.compare(make_fleet_doc("b", audit_mismatches=2),
                              doc_a=make_doc("a"))
        assert "fleet:audit_mismatch" in rep["regressions"]

    def test_zero_mismatches_is_clean(self):
        rep = regress.compare(make_fleet_doc("b"),
                              doc_a=make_fleet_doc("a"))
        assert rep["verdict"] == "ok"
        assert rep["phases"]["fleet:audit_mismatch"]["verdict"] == "ok"

    def test_hedge_win_rate_is_banded_not_hard(self):
        """Hedge wins are an operating condition — only a RISING win
        rate (tail degradation the hedge keeps rescuing) regresses."""
        a = make_fleet_doc("a", hedges=100, hedge_wins=5)
        same = make_fleet_doc("b", hedges=100, hedge_wins=5)
        rep = regress.compare(same, doc_a=a)
        assert rep["verdict"] == "ok"
        worse = make_fleet_doc("c", hedges=100, hedge_wins=50)
        rep = regress.compare(worse, doc_a=a)
        assert "fleet:hedge_win_rate" in rep["regressions"]
        assert not rep["phases"]["fleet:hedge_win_rate"].get("hard_axis")


class TestGate:
    def _store(self, tmp_path, scales):
        store = RunStore(tmp_path)
        for i, s in enumerate(scales):
            store.put(make_doc(f"run-{i}", s))
        return store

    def test_gate_passes_within_noise(self, tmp_path):
        store = self._store(tmp_path, [1.0, 1.01, 0.99])
        store.put(make_doc("new", 1.05))
        code, rep = regress.gate(store, store.get("new"))
        assert code == regress.GATE_PASS == 0
        assert rep["exit_code"] == 0

    def test_gate_fails_on_2x_slowdown(self, tmp_path):
        store = self._store(tmp_path, [1.0, 1.01, 0.99])
        store.put(make_doc("new", 2.0))
        code, rep = regress.gate(store, store.get("new"))
        assert code == regress.GATE_REGRESSION == 2
        assert rep["regressions"] == ["fusedSpMM"]

    def test_gate_no_baseline_exits_3(self, tmp_path):
        store = RunStore(tmp_path)
        store.put(make_doc("only", 1.0))
        code, rep = regress.gate(store, store.get("only"))
        assert code == regress.GATE_NO_DATA == 3
        assert rep["verdict"] == "no_data"

    def test_gate_ignores_other_keys(self, tmp_path):
        store = self._store(tmp_path, [1.0])
        store.put(make_doc("foreign", 0.1, key="key-z"))
        store.put(make_doc("new", 1.02))
        code, _ = regress.gate(store, store.get("new"))
        assert code == 0


class TestCli:
    def _seed(self, tmp_path):
        store = RunStore(tmp_path)
        store.put(make_doc("run-base", 1.0))
        store.put(make_doc("run-new", 2.0))
        return str(tmp_path)

    def test_compare_prints_delta_table(self, tmp_path, capsys):
        root = self._seed(tmp_path)
        assert cli.main(["compare", "run-base", "run-new",
                         "--store", root]) == 0
        out = capsys.readouterr().out
        # per-phase row with delta, throughput and comm columns
        assert "fusedSpMM" in out
        assert "+100.0" in out
        assert "GF/s" in out and "Mw/call" in out
        assert "regression" in out

    def test_compare_json(self, tmp_path, capsys):
        root = self._seed(tmp_path)
        assert cli.main(["compare", "latest~1", "latest", "--store", root,
                         "--json"]) == 0
        rep = json.loads(capsys.readouterr().out)
        assert rep["verdict"] == "regression"

    def test_gate_exit_codes_through_cli(self, tmp_path, capsys):
        root = self._seed(tmp_path)
        assert cli.main(["gate", "run-new", "--store", root]) == 2
        assert cli.main(["gate", "run-new", "--store", root,
                         "--threshold", "2.0"]) == 0
        capsys.readouterr()

    def test_gate_unknown_run_is_usage_error(self, tmp_path):
        with pytest.raises(SystemExit):
            cli.main(["gate", "nope", "--store", str(tmp_path)])

    def test_history_lists_runs(self, tmp_path, capsys):
        root = self._seed(tmp_path)
        assert cli.main(["history", "--store", root]) == 0
        out = capsys.readouterr().out
        assert "run-base" in out and "run-new" in out

    def test_report_html_selfcontained(self, tmp_path, capsys):
        root = self._seed(tmp_path)
        out_file = tmp_path / "dash.html"
        assert cli.main(["report-html", "--store", root,
                         "-o", str(out_file)]) == 0
        html = out_file.read_text()
        capsys.readouterr()
        assert html.startswith("<!doctype html>")
        assert "run-new" in html
        assert "fusedSpMM" in html
        # self-contained: no external references
        assert "http://" not in html and "https://" not in html
        assert 'src="data:image/png;base64,' in html  # embedded chart


class TestReportTraceExit:
    """Satellite: the trace validator's exit code is the contract."""

    def test_valid_trace_exits_zero(self, tmp_path, capsys):
        p = tmp_path / "good.jsonl"
        p.write_text(json.dumps({
            "type": "begin", "schema": 1, "run_id": "r", "t0_epoch": 0.0,
        }) + "\n")
        assert cli.main(["report-trace", str(p)]) == 0
        capsys.readouterr()

    def test_schema_violation_exits_nonzero(self, tmp_path, capsys):
        p = tmp_path / "bad.jsonl"
        p.write_text('{"type": "span", "name": "x"}\n')  # missing fields
        rc = cli.main(["report-trace", str(p)])
        assert rc == 2
        assert "invalid trace" in capsys.readouterr().err

    def test_missing_file_exits_nonzero(self, tmp_path, capsys):
        assert cli.main(["report-trace", str(tmp_path / "absent.jsonl")]) == 2
        capsys.readouterr()

    def test_no_strict_tolerates(self, tmp_path, capsys):
        p = tmp_path / "mixed.jsonl"
        p.write_text(
            json.dumps({"type": "begin", "schema": 1, "run_id": "r",
                        "t0_epoch": 0.0}) + "\n"
            + "not json at all\n"
        )
        assert cli.main(["report-trace", str(p), "--no-strict"]) == 0
        capsys.readouterr()


def make_trace_doc(run_id, *, coverage=1.0, delivered=50):
    doc = make_fleet_doc(run_id)
    doc["record"]["fleet"]["trace"] = {
        "coverage": coverage, "delivered": delivered,
        "complete": int(round(coverage * delivered)),
    }
    return doc


class TestTraceCoverageAxis:
    """PR-19 gate axis: ``fleet:trace_coverage`` is the SECOND
    zero-tolerance hard axis — a delivered reply whose merged fleet
    trace cannot reconstruct a complete router→attempt→replica chain
    is a lost-observability event, not noise."""

    def test_full_coverage_is_clean(self):
        rep = regress.compare(make_trace_doc("b"),
                              doc_a=make_trace_doc("a"))
        assert rep["verdict"] == "ok"
        assert rep["phases"]["fleet:trace_coverage"]["verdict"] == "ok"

    def test_coverage_loss_is_hard_regression(self):
        rep = regress.compare(make_trace_doc("b", coverage=0.98),
                              doc_a=make_trace_doc("a"))
        assert rep["verdict"] == "regression"
        assert "fleet:trace_coverage" in rep["regressions"]
        row = rep["phases"]["fleet:trace_coverage"]
        assert row["hard_axis"] is True
        assert row["attribution"] == "fleet"

    def test_coverage_loss_regresses_even_without_baseline(self):
        rep = regress.compare(make_trace_doc("b", coverage=0.5),
                              doc_a=make_doc("a"))
        assert "fleet:trace_coverage" in rep["regressions"]

    def test_untraced_fleet_doc_grows_no_axis(self):
        rep = regress.compare(make_fleet_doc("b"),
                              doc_a=make_fleet_doc("a"))
        assert "fleet:trace_coverage" not in rep["phases"]
