import numpy as np
import pytest

from distributed_sddmm_tpu.common import KernelMode, MatMode
from distributed_sddmm_tpu.parallel.cannon_sparse_25d import CannonSparse25D
from distributed_sddmm_tpu.utils import oracle
from distributed_sddmm_tpu.utils.coo import HostCOO


def _problem(M=64, N=48, seed=0):
    return HostCOO.erdos_renyi(M, N, 4, seed=seed, values="normal")


def _dense_inputs(alg):
    A = alg.dummy_initialize(MatMode.A)
    B = alg.dummy_initialize(MatMode.B)
    A_host = oracle.dummy_dense(alg.M_pad, alg.R)
    B_host = oracle.dummy_dense(alg.N_pad, alg.R)
    return A, B, A_host, B_host


CONFIGS = [2, 8]  # c on 8 devices: 2x2x2 and 1x1x8


def test_requirements():
    S = _problem()
    with pytest.raises(ValueError):
        CannonSparse25D(S, R=8, c=1)  # p/c = 8 not square
    with pytest.raises(ValueError):
        CannonSparse25D(S, R=6, c=2)  # sqrt(p/c)*c = 4 does not divide 6


def test_skewed_layout_roundtrip():
    """put/host converters and dummy init agree on the skewed R layout."""
    S = _problem()
    alg = CannonSparse25D(S, R=8, c=2)
    A = alg.dummy_initialize(MatMode.A)
    np.testing.assert_allclose(
        alg.host_a(A), oracle.dummy_dense(alg.M_pad, 8)[: alg.M], rtol=1e-6
    )
    rng = np.random.default_rng(1)
    X = rng.standard_normal((S.M, 8))
    np.testing.assert_allclose(alg.host_a(alg.put_a(X)), X, rtol=1e-6)


def test_transpose_shift_self_inverse():
    S = _problem()
    alg = CannonSparse25D(S, R=8, c=2)
    _, B, _, B_host = _dense_inputs(alg)
    _, B1 = alg.initial_shift(None, B, KernelMode.SDDMM_A)
    _, B2 = alg.de_shift(None, B1, KernelMode.SDDMM_A)
    np.testing.assert_allclose(np.asarray(B2), np.asarray(B), rtol=1e-6)


@pytest.mark.parametrize("c", CONFIGS)
def test_sddmm_a(c):
    S = _problem()
    alg = CannonSparse25D(S, R=8, c=c)
    A, B, A_host, B_host = _dense_inputs(alg)
    _, B_sh = alg.initial_shift(None, B, KernelMode.SDDMM_A)
    out = alg.sddmm_a(A, B_sh, alg.scatter_s_values(S.vals))
    np.testing.assert_allclose(
        alg.gather_s_values(out), oracle.sddmm(S, A_host, B_host), rtol=1e-4
    )


@pytest.mark.parametrize("c", CONFIGS)
def test_sddmm_b(c):
    S = _problem()
    alg = CannonSparse25D(S, R=8, c=c)
    A, B, A_host, B_host = _dense_inputs(alg)
    A_sh, _ = alg.initial_shift(A, None, KernelMode.SDDMM_B)
    out = alg.sddmm_b(A_sh, B, alg.scatter_st_values(S.transpose().vals))
    np.testing.assert_allclose(
        alg.gather_st_values(out),
        oracle.sddmm(S.transpose(), B_host, A_host),
        rtol=1e-4,
    )


@pytest.mark.parametrize("c", CONFIGS)
def test_spmm_a(c):
    S = _problem()
    alg = CannonSparse25D(S, R=8, c=c)
    A, B, A_host, B_host = _dense_inputs(alg)
    _, B_sh = alg.initial_shift(None, B, KernelMode.SPMM_A)
    out = alg.spmm_a(alg.like_a_matrix(0.0), B_sh, alg.scatter_s_values(S.vals))
    np.testing.assert_allclose(
        alg.host_a(out)[: S.M], oracle.spmm_a(S, B_host), rtol=1e-4, atol=1e-3
    )


@pytest.mark.parametrize("c", CONFIGS)
def test_spmm_b(c):
    S = _problem()
    alg = CannonSparse25D(S, R=8, c=c)
    A, B, A_host, B_host = _dense_inputs(alg)
    A_sh, _ = alg.initial_shift(A, None, KernelMode.SPMM_B)
    out = alg.spmm_b(A_sh, alg.like_b_matrix(0.0), alg.scatter_st_values(S.transpose().vals))
    np.testing.assert_allclose(
        alg.host_b(out)[: S.N], oracle.spmm_b(S, A_host), rtol=1e-4, atol=1e-3
    )


def test_fused_and_four_algorithm_fingerprints():
    """The full scratch.cpp protocol: all four algorithms produce the same
    spmmA fingerprint from dummy inputs."""
    from distributed_sddmm_tpu.parallel.cannon_dense_25d import CannonDense25D
    from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
    from distributed_sddmm_tpu.parallel.sparse_shift_15d import SparseShift15D

    S = _problem()
    fps = []

    alg = CannonSparse25D(S, R=8, c=2)
    A, B, _, _ = _dense_inputs(alg)
    _, B_sh = alg.initial_shift(None, B, KernelMode.SPMM_A)
    out = alg.spmm_a(alg.like_a_matrix(0.0), B_sh, alg.scatter_s_values(S.vals))
    fps.append(alg.fingerprint(alg.host_a(out)[: S.M]))

    alg = CannonDense25D(S, R=8, c=2)
    A, B, _, _ = _dense_inputs(alg)
    out = alg.spmm_a(alg.like_a_matrix(0.0), B, alg.scatter_s_values(S.transpose().vals))
    out, _ = alg.de_shift(out, None, KernelMode.SPMM_A)
    fps.append(alg.fingerprint(alg.host_a(out)[: S.M]))

    for alg in (DenseShift15D(S, R=8, c=2), SparseShift15D(S, R=8, c=4)):
        A = alg.dummy_initialize(MatMode.A)
        B = alg.dummy_initialize(MatMode.B)
        out = alg.spmm_a(A, B, alg.scatter_s_values(S.vals))
        fps.append(alg.fingerprint(alg.host_a(out)[: S.M]))

    np.testing.assert_allclose(fps, fps[0], rtol=1e-5)


def test_rolled_matches_unrolled():
    S = _problem()
    res = []
    for unroll in (True, False):
        alg = CannonSparse25D(S, R=8, c=2, unroll=unroll)
        A, B, _, _ = _dense_inputs(alg)
        _, B_sh = alg.initial_shift(None, B, KernelMode.SDDMM_A)
        out = alg.sddmm_a(A, B_sh, alg.scatter_s_values(S.vals))
        res.append(alg.gather_s_values(out))
    np.testing.assert_allclose(res[0], res[1], rtol=1e-5)
