"""Pallas kernel path: blocked encoding + one-hot MXU kernels.

Runs in Pallas interpreter mode on the CPU test mesh (the same code compiles
to Mosaic on TPU). Mirrors the reference's kernel verification strategy —
numeric agreement with an oracle (SURVEY.md section 4) — plus cross-kernel
fingerprint equality between the XLA and Pallas implementations of the
distributed ops (`/root/reference/scratch.cpp:26-76`).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from distributed_sddmm_tpu.common import MatMode
from distributed_sddmm_tpu.ops.blocked import CHUNK, build_blocked, unpack_meta
from distributed_sddmm_tpu.ops.kernels import XlaKernel, get_kernel
from distributed_sddmm_tpu.ops.pallas_kernels import BlockedTile, PallasKernel
from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
from distributed_sddmm_tpu.utils.coo import HostCOO
from distributed_sddmm_tpu.utils import oracle


def sddmm_oracle(rows, cols, vals, A, B):
    S = HostCOO(rows, cols, vals, A.shape[0], B.shape[0])
    return oracle.sddmm(S, A.astype(np.float64), B.astype(np.float64))


def spmm_oracle(rows, cols, vals, B, out_rows):
    S = HostCOO(rows, cols, vals, out_rows, B.shape[0])
    return oracle.spmm_a(S, B.astype(np.float64))


def _tile_setup(Mr=700, Nc=500, nnz=3000, seed=0, group=1):
    rng = np.random.default_rng(seed)
    rows = rng.integers(0, Mr, nnz).astype(np.int64)
    cols = rng.integers(0, Nc, nnz).astype(np.int64)
    bucket = np.zeros(nnz, dtype=np.int64)
    meta = build_blocked(1, bucket, rows, cols, Mr, Nc, group=group)
    blk = BlockedTile(
        lr=jnp.array(meta.lr[0]),
        lc=jnp.array(meta.lc[0]),
        meta=jnp.array(meta.meta[0]),
        bm=meta.bm, bn=meta.bn,
        gr_blocks=meta.gr_blocks, gc_blocks=meta.gc_blocks,
        group=meta.group,
    )
    max_nnz = meta.n_chunks * CHUNK
    vals = np.zeros(max_nnz, np.float32)
    vals[meta.host_to_chunk] = rng.standard_normal(nnz).astype(np.float32)
    return rows, cols, meta, blk, vals, rng


class TestBlockedMeta:
    def test_chunk_invariants(self):
        rows, cols, meta, _, _, _ = _tile_setup()
        # Every nonzero lands in the right block.
        gr, gc, first, last = unpack_meta(meta.meta[0])
        ch = meta.host_to_chunk // CHUNK
        assert np.all(gr[ch] == rows // meta.bm)
        assert np.all(gc[ch] == cols // meta.bn)
        # Every gr group has exactly one first and one last flag, and real
        # (flagged-or-populated) chunks are sorted by (gr, gc) — the
        # accumulator zero/flush contract of the kernels.
        assert first.sum() == meta.gr_blocks
        assert last.sum() == meta.gr_blocks
        real = np.zeros(gr.shape, dtype=bool)
        real[np.unique(ch)] = True
        real |= (first | last).astype(bool)
        key = gr[real] * meta.gc_blocks + gc[real]
        assert np.all(np.diff(key) >= 0)
        # global_rows/global_cols reproduce the original coordinates.
        grows = meta.global_rows().reshape(-1)
        gcols = meta.global_cols().reshape(-1)
        assert np.all(grows[meta.host_to_chunk] == rows)
        assert np.all(gcols[meta.host_to_chunk] == cols)
        # Pad lanes are marked and zeroed.
        pads = meta.pad_lane.reshape(-1)
        assert pads.sum() == meta.n_chunks * CHUNK - rows.size
        assert np.all(grows[pads] == 0)

    def test_meta_word_gr_no_sign_extension(self):
        # gr occupies the int32 sign-adjacent bits; unpack must mask, not
        # arithmetic-shift (regression: gr=16384 came back as -16384).
        from distributed_sddmm_tpu.ops.blocked import pack_meta

        w = pack_meta(
            np.array([16384]), np.array([7]), np.array([1]), np.array([0])
        )
        gr, gc, first, last = unpack_meta(w)
        assert (gr[0], gc[0], first[0], last[0]) == (16384, 7, 1, 0)

    def test_pad_chunks_pin_last_output_window(self):
        # Buckets shorter than the shared C get trailing pad chunks; their
        # meta must keep the output window on the LAST row block (an
        # unwritten remapped window would flush stale VMEM over block 0).
        rng = np.random.default_rng(2)
        nnz = 4000
        rows = rng.integers(0, 1500, nnz).astype(np.int64)
        cols = rng.integers(0, 1500, nnz).astype(np.int64)
        bucket = (np.arange(nnz) < 100).astype(np.int64)  # very uneven
        meta = build_blocked(2, bucket, rows, cols, 1500, 1500)
        gr, gc, first, last = unpack_meta(meta.meta)
        n_chunks_b1 = int(
            (~meta.pad_lane[1].all(axis=1)).sum()
        )  # chunks with any real lane
        assert n_chunks_b1 < meta.n_chunks  # pads exist for this test
        trailing = gr[1, np.where(last[1])[0].max() + 1 :]
        assert np.all(trailing == meta.gr_blocks - 1)

    @pytest.mark.parametrize("group", [2, 4, 8])
    def test_group_alignment(self, group):
        # With chunk grouping, a kernel grid step (group consecutive chunks)
        # must never straddle a row-block window: C is a multiple of the
        # group and every step's chunks share one gr.
        rows, cols, meta, _, _, _ = _tile_setup(group=group)
        assert meta.group == group
        assert meta.n_chunks % group == 0
        gr, gc, first, last = unpack_meta(meta.meta[0])
        steps = gr.reshape(-1, group)
        assert np.all(steps == steps[:, :1])
        # Flag counts survive the deficit padding (one zero + one flush per
        # gr group; the flush may sit on a trailing pad chunk by design).
        assert first.sum() == meta.gr_blocks
        assert last.sum() == meta.gr_blocks
        # Coordinates still round-trip.
        assert np.all(meta.global_rows().reshape(-1)[meta.host_to_chunk] == rows)
        assert np.all(meta.global_cols().reshape(-1)[meta.host_to_chunk] == cols)

    def test_every_gr_flushed_for_empty_rows(self):
        # Matrix with nonzeros only in the top rows: lower row blocks must
        # still get first/last chunks so the output is zeroed.
        rng = np.random.default_rng(1)
        rows = rng.integers(0, 100, 500).astype(np.int64)
        cols = rng.integers(0, 2000, 500).astype(np.int64)
        meta = build_blocked(1, np.zeros(500, np.int64), rows, cols, 4000, 2000)
        _, _, first, last = unpack_meta(meta.meta[0])
        assert first.sum() == meta.gr_blocks
        assert last.sum() == meta.gr_blocks


class TestPallasTileKernels:
    # Slow-marked rows are single-axis redundancies: every axis keeps a
    # fast representative — grouping×form×batch interactions stay via
    # (4,bt,True)/(4,nt,True)/(8,bt,True), ungrouped bases via
    # (1,bt,False)/(1,nt,False), bf16 via both its rows.
    @pytest.mark.parametrize(
        "precision,tol,group,form,batch",
        [
            ("f32", 1e-5, 1, "bt", False),
            ("bf16", 3e-2, 1, "bt", False),
            pytest.param("f32", 1e-5, 4, "bt", False,
                         marks=pytest.mark.slow),
            ("f32", 1e-5, 1, "nt", False),
            pytest.param("f32", 1e-5, 4, "nt", False,
                         marks=pytest.mark.slow),
            pytest.param("f32", 1e-5, 1, "bt", True,
                         marks=pytest.mark.slow),
            ("f32", 1e-5, 4, "bt", True),
            ("f32", 1e-5, 4, "nt", True),
            ("f32", 1e-5, 8, "bt", True),
            ("bf16", 3e-2, 4, "bt", True),
        ],
    )
    def test_against_oracle(self, precision, tol, group, form, batch):
        rows, cols, meta, blk, vals, rng = _tile_setup(group=group)
        Mr, Nc, R = 700, 500, 32
        A = rng.standard_normal((Mr, R)).astype(np.float32)
        B = rng.standard_normal((Nc, R)).astype(np.float32)
        k = PallasKernel(precision=precision, interpret=True,
                         scatter_form=form, batch_step=batch)
        vj, Aj, Bj = jnp.array(vals), jnp.array(A), jnp.array(B)

        host_vals = vals[meta.host_to_chunk]
        ref_mid = sddmm_oracle(rows, cols, host_vals, A, B)
        mid = np.asarray(k.sddmm_tile(blk, vj, Aj, Bj))
        scale = np.abs(ref_mid).max() + 1
        np.testing.assert_allclose(
            mid[meta.host_to_chunk] / scale, ref_mid / scale, atol=tol
        )
        # Pad lanes stay exactly zero.
        assert np.all(mid[meta.pad_lane.reshape(-1)] == 0)

        ref_out = spmm_oracle(rows, cols, host_vals, B, Mr)
        out = np.asarray(k.spmm_tile(blk, vj, Bj, Mr))
        scale = np.abs(ref_out).max() + 1
        np.testing.assert_allclose(out / scale, ref_out / scale, atol=tol)

        fo, fm = k.fused_tile(blk, vj, Aj, Bj)
        ref_fo = spmm_oracle(rows, cols, ref_mid, B, Mr)
        scale = np.abs(ref_fo).max() + 1
        np.testing.assert_allclose(np.asarray(fo) / scale, ref_fo / scale, atol=tol)
        np.testing.assert_allclose(
            np.asarray(fm)[meta.host_to_chunk] / (np.abs(ref_mid).max() + 1),
            ref_mid / (np.abs(ref_mid).max() + 1),
            atol=tol,
        )

    def test_flat_protocol_fallback(self):
        # PallasKernel is a drop-in LocalKernel: flat calls route to XLA.
        k = PallasKernel(interpret=True)
        rows = jnp.array([0, 1, 1], jnp.int32)
        cols = jnp.array([0, 0, 2], jnp.int32)
        vals = jnp.array([1.0, 2.0, 3.0])
        A = jnp.ones((2, 4))
        B = jnp.ones((3, 4))
        ref = XlaKernel()
        np.testing.assert_allclose(
            k.sddmm(rows, cols, vals, A, B), ref.sddmm(rows, cols, vals, A, B)
        )
        np.testing.assert_allclose(
            k.spmm(rows, cols, vals, B, 2), ref.spmm(rows, cols, vals, B, 2)
        )

    def test_factory(self):
        assert get_kernel("pallas").name.startswith("pallas")


class TestPallasDistributed:
    """XLA and Pallas kernels must produce identical fingerprints through
    the full distributed 1.5D dense-shift programs."""

    # Off-diagonal (c, fusion) combos are slow-marked: the c axis and
    # the fusion axis each keep a fast representative on the diagonal,
    # and kernel identity is per-axis, not per-cross-product.
    @pytest.mark.parametrize(
        "c,fusion",
        [
            (1, 1),
            pytest.param(1, 2, marks=pytest.mark.slow),
            pytest.param(2, 1, marks=pytest.mark.slow),
            (2, 2),
        ],
    )
    def test_fingerprints_match_xla(self, c, fusion):
        S = HostCOO.erdos_renyi(260, 220, 5, seed=3, values="normal")
        algs = [
            DenseShift15D(S, R=16, c=c, fusion_approach=fusion, kernel=XlaKernel()),
            DenseShift15D(
                S, R=16, c=c, fusion_approach=fusion,
                kernel=PallasKernel(precision="f32", interpret=True),
            ),
        ]
        prints = []
        for alg in algs:
            A = alg.dummy_initialize(MatMode.A)
            B = alg.dummy_initialize(MatMode.B)
            out, mid = alg.fused_spmm(A, B, alg.like_s_values(1.0))
            prints.append(
                (
                    alg.fingerprint(alg.host_a(out)),
                    alg.fingerprint(alg.gather_s_values(mid)),
                    alg.fingerprint(alg.host_b(alg.spmm_b(A, B, alg.like_st_values(1.0)))),
                )
            )
        np.testing.assert_allclose(prints[0], prints[1], rtol=1e-5)


class TestPallasAllAlgorithms:
    """Every strategy (including the tile-rotating and fiber-replicated
    ones) runs its ops through the blocked Pallas kernels with fingerprints
    identical to the XLA path — the scratch.cpp protocol across kernels."""

    # The (c=2, p=8) rows are slow-marked: each algorithm keeps its
    # fast pallas-vs-xla identity representative at (1, 4), and
    # replication's interaction with the blocked kernels stays covered
    # fast by TestPallasDistributed's c=2 row.
    @pytest.mark.parametrize(
        "alg_name,c,p",
        [
            ("15d_sparse", 1, 4),
            pytest.param("15d_sparse", 2, 8, marks=pytest.mark.slow),
            ("25d_dense_replicate", 1, 4),
            pytest.param("25d_dense_replicate", 2, 8,
                         marks=pytest.mark.slow),
            ("25d_sparse_replicate", 1, 4),
            pytest.param("25d_sparse_replicate", 2, 8,
                         marks=pytest.mark.slow),
        ],
    )
    def test_fingerprints_match_xla(self, alg_name, c, p):
        import jax

        from distributed_sddmm_tpu.common import KernelMode
        from distributed_sddmm_tpu.bench.harness import make_algorithm

        S = HostCOO.erdos_renyi(280, 260, 5, seed=4, values="normal")
        devices = jax.devices()[:p]
        prints = []
        for kern in (
            XlaKernel(),
            PallasKernel(precision="f32", interpret=True),
        ):
            alg = make_algorithm(alg_name, S, R=16, c=c, kernel=kern,
                                 devices=devices)
            A = alg.dummy_initialize(MatMode.A)
            B = alg.dummy_initialize(MatMode.B)
            A_s, B_s = alg.initial_shift(A, B, KernelMode.SDDMM_A)
            mid = alg.sddmm_a(A_s, B_s, alg.like_s_values(1.0))
            zero, B_s2 = alg.initial_shift(
                alg.like_a_matrix(0.0), B, KernelMode.SPMM_A
            )
            out = alg.spmm_a(zero, B_s2, alg.like_s_values(1.0))
            out, _ = alg.de_shift(out, None, KernelMode.SPMM_A)
            A_s3, zb = alg.initial_shift(
                A, alg.like_b_matrix(0.0), KernelMode.SPMM_B
            )
            outb = alg.spmm_b(A_s3, zb, alg.like_st_values(1.0))
            _, outb = alg.de_shift(None, outb, KernelMode.SPMM_B)
            A_s4, B_s4 = alg.initial_shift(A, B, KernelMode.SDDMM_B)
            mid_b = alg.sddmm_b(A_s4, B_s4, alg.like_st_values(1.0))
            prints.append(
                (
                    alg.fingerprint(alg.gather_s_values(mid)),
                    alg.fingerprint(alg.host_a(out)),
                    alg.fingerprint(alg.host_b(outb)),
                    alg.fingerprint(alg.gather_st_values(mid_b)),
                )
            )
        np.testing.assert_allclose(prints[0], prints[1], rtol=1e-5)
