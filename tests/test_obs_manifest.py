"""Manifest git-provenance contract: detached HEADs and non-git
checkouts degrade to explicit markers, never exceptions.

A manifest is written on every traced run, possibly from a tarball
export or a CI sandbox with no ``.git`` (or no git binary at all) — the
bench must keep running and the manifest must say *why* provenance is
absent (``git_rev: "unknown"``) rather than crash or emit an ambiguous
null.
"""

import subprocess

from distributed_sddmm_tpu.obs import manifest


def _fresh(monkeypatch):
    """Clear the per-directory memo so each test measures a real probe."""
    monkeypatch.setattr(manifest, "_git_info_cache", {})


class TestGitInfo:
    def test_real_checkout_resolves_rev_and_dirty_flag(self, monkeypatch):
        _fresh(monkeypatch)
        info = manifest._git_info()
        assert len(info["git_rev"]) == 40  # a real sha, this repo is git
        assert info["git_dirty"] in (True, False)

    def test_non_git_directory_records_unknown(self, monkeypatch, tmp_path):
        _fresh(monkeypatch)
        info = manifest._git_info(cwd=tmp_path)
        assert info == {"git_rev": "unknown", "git_dirty": None}

    def test_detached_head_still_resolves(self, monkeypatch, tmp_path):
        """rev-parse HEAD works on a detached HEAD; the manifest must
        record the sha, not 'unknown'."""
        _fresh(monkeypatch)
        for cmd in (
            ["git", "init", "-q"],
            ["git", "-c", "user.email=t@t", "-c", "user.name=t",
             "commit", "-q", "--allow-empty", "-m", "one"],
            ["git", "-c", "user.email=t@t", "-c", "user.name=t",
             "commit", "-q", "--allow-empty", "-m", "two"],
            ["git", "checkout", "-q", "--detach", "HEAD~1"],
        ):
            subprocess.run(cmd, cwd=tmp_path, check=True,
                           capture_output=True)
        info = manifest._git_info(cwd=tmp_path)
        assert len(info["git_rev"]) == 40
        assert info["git_dirty"] is False

    def test_missing_git_binary_never_raises(self, monkeypatch):
        _fresh(monkeypatch)

        def boom(*a, **kw):
            raise FileNotFoundError("git not on PATH")

        monkeypatch.setattr(manifest.subprocess, "run", boom)
        info = manifest._git_info()
        assert info == {"git_rev": "unknown", "git_dirty": None}

    def test_build_carries_both_fields_and_never_raises(self, monkeypatch):
        _fresh(monkeypatch)
        monkeypatch.setattr(
            manifest, "_REPO", manifest._REPO / "no-such-subdir"
        )
        m = manifest.build("run-x")
        assert m["git_rev"] == "unknown"
        assert m["git_dirty"] is None
        assert m["run_id"] == "run-x"

    def test_memoized_per_directory(self, monkeypatch, tmp_path):
        _fresh(monkeypatch)
        manifest._git_info(cwd=tmp_path)
        calls = []
        monkeypatch.setattr(
            manifest.subprocess, "run",
            lambda *a, **kw: calls.append(a) or (_ for _ in ()).throw(
                AssertionError("should be memoized")
            ),
        )
        assert manifest._git_info(cwd=tmp_path)["git_rev"] == "unknown"
        assert not calls
