import numpy as np

from distributed_sddmm_tpu.utils import oracle
from distributed_sddmm_tpu.utils.coo import HostCOO


def _setup(M=24, N=16, R=8, seed=0):
    S = HostCOO.erdos_renyi(M, N, 4, seed=seed, values="normal")
    rng = np.random.default_rng(seed + 1)
    A = rng.standard_normal((M, R))
    B = rng.standard_normal((N, R))
    return S, A, B


def test_sddmm_matches_dense():
    S, A, B = _setup()
    dense = A @ B.T
    expected = S.vals * dense[S.rows, S.cols]
    np.testing.assert_allclose(oracle.sddmm(S, A, B), expected, rtol=1e-12)


def test_spmm_a_matches_dense():
    S, A, B = _setup()
    expected = S.to_scipy() @ B
    np.testing.assert_allclose(oracle.spmm_a(S, B), expected, rtol=1e-12)


def test_spmm_b_matches_dense():
    S, A, B = _setup()
    expected = S.to_scipy().T @ A
    np.testing.assert_allclose(oracle.spmm_b(S, A), expected, rtol=1e-12)


def test_spmm_accumulates():
    S, A, B = _setup()
    out = oracle.spmm_a(S, B, A_in=A)
    np.testing.assert_allclose(out, A + S.to_scipy() @ B, rtol=1e-12)


def test_fused():
    S, A, B = _setup()
    mid = oracle.sddmm(S, A, B)
    np.testing.assert_allclose(
        oracle.fused_spmm_a(S, A, B),
        S.with_values(mid).to_scipy() @ B,
        rtol=1e-12,
    )
    np.testing.assert_allclose(
        oracle.fused_spmm_b(S, A, B),
        S.with_values(mid).to_scipy().T @ A,
        rtol=1e-12,
    )


def test_dummy_dense_and_fingerprint():
    X = oracle.dummy_dense(4, 3)
    assert X[2, 1] == 2 * 3 + 1
    assert oracle.fingerprint(np.array([1.0, 2.0])) == 5.0
