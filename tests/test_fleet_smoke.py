"""Tier-1 smoke for the serving fleet (ISSUE 16 acceptance).

Runs ``scripts/fleet_smoke.py`` as a subprocess — ``bench fleet`` with
a chaos kill at the load midpoint: replies must stay bit-identical to
the single-engine oracle, nothing may be lost (re-admitted by router
failover or shed with Retry-After), the replacement replica must
warm-start from the shared ProgramStore with 0 request-path compiles,
and the record must carry the ``fleet:availability`` gate axis. Exit
contract 0 (all green) / 2 (any check red).
"""

import json
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parents[1]
SCRIPT = REPO / "scripts" / "fleet_smoke.py"


# Slow-marked to fund the tier-1 budget for the chaos drill
# (tests/test_chaos_smoke.py), which subsumes this run's contract —
# kill + warm respawn + oracle bit-identity + availability + gate
# axes — under a four-fault schedule. The ``kill-replica`` sugar this
# script passes is pinned at the grammar level by
# tests/test_chaos_schedule.py, and tenant accounting by test_serve.py.
@pytest.mark.slow
def test_fleet_smoke_script(tmp_path):
    out = tmp_path / "fleet_smoke.json"
    proc = subprocess.run(
        [sys.executable, str(SCRIPT), "-o", str(out)],
        capture_output=True, text=True, timeout=600, cwd=REPO,
        env={
            "PATH": "/usr/bin:/bin",
            "HOME": "/tmp",
            "JAX_PLATFORMS": "cpu",
            "DSDDMM_RUNSTORE": "0",
            "DSDDMM_PROGRAMS": "0",
        },
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(out.read_text())
    assert report["ok"] is True
    (chaos,) = report["checks"]

    assert chaos["exit_code"] == 0
    assert chaos["mismatches"] == 0  # bit-identical through the kill
    assert chaos["lost"] == 0       # re-admitted or shed-with-retry
    assert chaos["killed"]          # the chaos actually fired
    assert chaos["replacement_live_compiles"] == 0  # warm respawn
    assert chaos["replacement_disk_hits"] > 0
    assert chaos["availability"] >= 0.95
    assert "fleet:availability" in chaos["gate_axes"]
    # Per-tenant accounting survives the fleet rollup. The SIGKILLed
    # replica's recorder dies with it, so attribution may undercount
    # the client's ok tally by what the victim had served — but never
    # overcount, and never go dark.
    assert 0 < chaos["tenant_requests"] <= chaos["ok_replies"]


def test_exit_code_contract():
    """The 0/2 contract without a second subprocess run."""
    sys.path.insert(0, str(REPO / "scripts"))
    try:
        import fleet_smoke
    finally:
        sys.path.pop(0)
    assert fleet_smoke.exit_code({"ok": True}) == 0
    assert fleet_smoke.exit_code({"ok": False}) == 2
    assert fleet_smoke.exit_code({}) == 2
