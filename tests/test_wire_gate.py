"""Structural HLO gate for the wire-precision layer (tier-1 acceptance,
``test_multihost_gate.py`` style).

The fused dense-shift pair, AOT-compiled for a REAL v5e topology under
the bf16 wire policy, must carry bf16 element types on its
``all-gather`` and ``collective-permute`` collectives while the
``reduce-scatter`` stays f32 (always-f32 accumulation), and the f32
module must carry no bf16 collective at all. Counted in-model
``comm_bytes`` must drop to <= 0.55x under bf16 on the headline
config, the bf16 run must match the float64 oracle within the
documented bound, and must replay bitwise (tuner shadow-compare
contract). The committed ``WIRE_HLO.json`` is the banked record.

Subprocess + ``TPU_SKIP_MDS_QUERY=1`` for the same libtpu metadata
reason as the other gates.
"""

import json
import os
import pathlib
import subprocess
import sys

from distributed_sddmm_tpu.parallel.wire_hlo import scan_collective_dtypes

REPO = pathlib.Path(__file__).resolve().parents[1]

_PROBE = """
import json, sys
sys.path.insert(0, {repo!r})
from distributed_sddmm_tpu.utils.platform import force_cpu_platform
force_cpu_platform(n_devices=8, replace=True)
from distributed_sddmm_tpu.parallel.wire_hlo import wire_hlo_report
print("RESULT " + json.dumps(wire_hlo_report()))
"""


def test_wire_fused_pair_v5e_hlo_gate():
    env = dict(os.environ)
    env.update({
        "TPU_SKIP_MDS_QUERY": "1",
        "DSDDMM_PROGRAMS": "0",
        "DSDDMM_RUNSTORE": "0",
        "PYTHONPATH": str(REPO),
    })
    proc = subprocess.run(
        [sys.executable, "-c", _PROBE.format(repo=str(REPO))],
        capture_output=True, text=True, timeout=540, env=env,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    line = [l for l in proc.stdout.splitlines() if l.startswith("RESULT ")]
    assert line, proc.stdout[-2000:]
    rec = json.loads(line[0][len("RESULT "):])
    assert rec["topology"] == "v5e:2x4" and rec["is_scheduled"] is True
    assert rec["unparsed_lines"] == 0, rec

    # The acceptance bar: bf16 element types on the gather + ring
    # collectives, f32 on the reduce-scatter, and a module-wide clean
    # f32 story for the identity wire.
    b16 = rec["collectives_bf16"]
    assert b16["all-gather"]["dtypes"].get("bf16", 0) >= 1, b16
    assert b16["collective-permute"]["dtypes"].get("bf16", 0) >= 1, b16
    assert b16["reduce-scatter"]["dtypes"] == \
        {"f32": b16["reduce-scatter"]["count"]}, b16
    for op, entry in rec["collectives_f32"].items():
        assert entry["dtypes"] == {"f32": entry["count"]}, (op, entry)

    # Counted bytes: <= 0.55x on the headline dense-shift fused config
    # (the in-model payloads are all gather/ring, so the realized ratio
    # is exactly 0.5).
    assert rec["bytes_ratio"] <= 0.55, rec["bytes_ratio"]
    # Oracle + determinism: the documented bf16 accuracy bound and the
    # replay-stability the tuner's bitwise shadow-compare relies on.
    assert rec["oracle_rel_err_bf16"] <= 1e-2, rec
    assert rec["oracle_rel_err_f32"] <= 1e-6, rec
    assert rec["bf16_deterministic"] is True

    # Matches the committed banked record on every structural field.
    committed = json.loads((REPO / "WIRE_HLO.json").read_text())
    for field in ("topology", "p", "c", "M", "nnz", "R",
                  "collectives_f32", "collectives_bf16",
                  "unparsed_lines", "bytes_ratio", "bf16_deterministic"):
        assert rec[field] == committed[field], (field, rec, committed)


# --------------------------------------------------------------------- #
# The dtype scanner's own contract on synthetic HLO
# --------------------------------------------------------------------- #

_HLO = """\
HloModule jit_prog, is_scheduled=true

%body (arg: f32[8]) -> f32[8] {
  %ag = bf16[8] all-gather(bf16[4] %x), replica_groups={{0,1}}, channel_id=1
  %cps = (bf16[8], bf16[8]) collective-permute-start(bf16[8] %y), source_target_pairs={{0,1},{1,0}}
  %cpd = bf16[8] collective-permute-done((bf16[8], bf16[8]) %cps)
  %rs = f32[4] reduce-scatter(f32[8] %z), replica_groups={{0,1}}, dimensions={0}
  ROOT %r = f32[8] add(%a, %b)
}
"""


def test_scanner_reads_element_dtypes_and_counts_starts_once():
    scan = scan_collective_dtypes(_HLO)
    assert scan["per_op"]["all-gather"] == {
        "count": 1, "dtypes": {"bf16": 1},
    }
    # -start counted once (the -done names no fresh collective); the
    # tuple result's payload dtype is read.
    assert scan["per_op"]["collective-permute"] == {
        "count": 1, "dtypes": {"bf16": 1},
    }
    assert scan["per_op"]["reduce-scatter"] == {
        "count": 1, "dtypes": {"f32": 1},
    }
    assert scan["unparsed_lines"] == 0


def test_scanner_empty_hlo():
    scan = scan_collective_dtypes("")
    assert scan["per_op"] == {} and scan["unparsed_lines"] == 0
