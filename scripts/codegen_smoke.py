"""Codegen smoke: variant selection, banked waste reduction, store keys.

One process, four sections, JSON report (the tier-1 test
``tests/test_codegen_smoke.py`` asserts on it):

* **selection** — the fingerprint-selected variant registers as an
  autotune candidate beside the generic Pallas kernel, its id
  round-trips through ``variant_from_id`` and through a ``Plan``
  record, and the cost model discounts it on the skewed problem.
* **waste** — on a skewed (R-mat) single-bucket tile, the banked
  encoding cuts counted padded lanes >= 2x vs the generic encoding,
  with BIT-IDENTICAL fused SDDMM->SpMM results (integer-valued data:
  every f32 sum is exact, so reassociation cannot hide behind
  tolerance) on the CPU Pallas interpreter.
* **store** — a plan carrying the variant id binds its strategy to a
  ProgramStore: the variant id appears in the program key, a second
  strategy against the same root warms from disk with zero live
  compiles, the GENERIC plan's key never aliases the variant's, and a
  corrupted (stale) variant entry evicts-and-recompiles instead of
  serving garbage.
* **record** — a one-trial bench run under the banked kernel carries
  ``kernel_variant`` and the per-op ``padded_lane_frac`` metric.

Usage::

    python scripts/codegen_smoke.py [-o out.json]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import tempfile

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-o", "--output-file", default=None)
    args = ap.parse_args()

    from distributed_sddmm_tpu.utils.platform import force_cpu_platform

    force_cpu_platform(n_devices=8, replace=True)

    import numpy as np
    import jax.numpy as jnp

    from distributed_sddmm_tpu import codegen, programs
    from distributed_sddmm_tpu.autotune.candidates import enumerate_candidates
    from distributed_sddmm_tpu.autotune.fingerprint import Problem
    from distributed_sddmm_tpu.autotune.plan import Plan
    from distributed_sddmm_tpu.bench.harness import benchmark_algorithm
    from distributed_sddmm_tpu.obs import metrics as obs_metrics
    from distributed_sddmm_tpu.ops.blocked import (
        CHUNK, DEFAULT_GROUP, build_blocked,
    )
    from distributed_sddmm_tpu.ops.pallas_kernels import (
        BlockedTile, PallasKernel,
    )
    from distributed_sddmm_tpu.utils.coo import HostCOO

    report: dict = {}

    # ------------------------------------------------------------------ #
    # 1. Selection: variant candidates, id and plan round-trips
    # ------------------------------------------------------------------ #
    S = HostCOO.rmat(log_m=13, edge_factor=4, seed=0)
    problem = Problem.from_coo(S, R=64)
    variant = codegen.select_variant(problem)
    vid = variant.variant_id

    cands = enumerate_candidates(problem, p=8, kernels=("pallas", "xla"))
    variant_cands = [c for c in cands if c.variant]
    assert any(c.variant == vid for c in variant_cands), (vid, cands[:5])
    rebuilt = codegen.variant_from_id(vid)
    assert rebuilt == variant, (rebuilt, variant)
    plan = Plan(algorithm="15d_fusion2", c=1, kernel="pallas", variant=vid,
                fingerprint_key="fp-codegen-smoke")
    assert Plan.from_dict(plan.to_dict()).variant == vid
    factor = codegen.variant_cost_factor(problem, vid)
    report["selection"] = {
        "variant": vid,
        "bands": [
            {"npr_max": b.npr_max, "body": b.body} for b in variant.bands
        ],
        "variant_candidates": len(variant_cands),
        "cost_factor": factor,
    }
    assert factor < 1.0, factor  # skewed problem: banking must rank better

    # ------------------------------------------------------------------ #
    # 2. Waste reduction + bit identity on the skewed tile
    # ------------------------------------------------------------------ #
    rows = S.rows.astype(np.int64)
    cols = S.cols.astype(np.int64)
    bucket = np.zeros(S.nnz, np.int64)
    gen = build_blocked(1, bucket, rows, cols, S.M, S.N, group=DEFAULT_GROUP)
    ban = codegen.build_banded(1, bucket, rows, cols, S.M, S.N, variant)
    waste_gen = codegen.padded_lane_count(gen)
    waste_ban = codegen.padded_lane_count(ban)
    ratio = waste_gen / max(waste_ban, 1)

    rng = np.random.default_rng(0)
    R = 32
    vals_h = rng.integers(-4, 5, S.nnz).astype(np.float32)
    A = jnp.array(rng.integers(-3, 4, (S.M, R)).astype(np.float32))
    B = jnp.array(rng.integers(-3, 4, (S.N, R)).astype(np.float32))

    def chunk_vals(meta):
        v = np.zeros(meta.n_chunks * CHUNK, np.float32)
        v[meta.host_to_chunk] = vals_h
        return jnp.array(v)

    tile_g = BlockedTile(
        lr=jnp.array(gen.lr[0]), lc=jnp.array(gen.lc[0]),
        meta=jnp.array(gen.meta[0]), bm=gen.bm, bn=gen.bn,
        gr_blocks=gen.gr_blocks, gc_blocks=gen.gc_blocks, group=gen.group,
    )
    tile_b = codegen.BankedTile(
        lr=jnp.array(ban.lr[0]), lc=jnp.array(ban.lc[0]),
        meta=jnp.array(ban.meta[0]), bands=ban.bands,
        rows_pad=ban.rows_pad, cols_pad=ban.cols_pad,
    )
    kern_g = PallasKernel(precision="f32", interpret=True)
    kern_b = codegen.BankedPallasKernel(variant, precision="f32",
                                        interpret=True)
    out_g, mid_g = kern_g.fused_tile(tile_g, chunk_vals(gen), A, B)
    out_b, mid_b = kern_b.fused_tile(tile_b, chunk_vals(ban), A, B)
    bit_identical = bool(
        np.array_equal(np.asarray(out_g), np.asarray(out_b))
        and np.array_equal(
            np.asarray(mid_g)[gen.host_to_chunk],
            np.asarray(mid_b)[ban.host_to_chunk],
        )
    )
    report["waste"] = {
        "pad_lanes_generic": waste_gen,
        "pad_lanes_banked": waste_ban,
        "reduction_ratio": ratio,
        "bit_identical": bit_identical,
        "bands": [
            {"body": b.body, "bn": b.bn, "chunks": b.c1 - b.c0}
            for b in ban.bands
        ],
    }
    assert ratio >= 2.0, report["waste"]
    assert bit_identical, report["waste"]

    # ------------------------------------------------------------------ #
    # 3. ProgramStore round-trip with variant-id keys
    # ------------------------------------------------------------------ #
    store_root = pathlib.Path(tempfile.mkdtemp(prefix="codegen_store_"))
    S_small = HostCOO.erdos_renyi(64, 48, 6, seed=0, values="normal")

    def run_plan(p, root):
        store = programs.ProgramStore(root)
        before = store.stats()
        alg = p.instantiate(S_small, R=8, program_store=store)
        A0 = alg.dummy_initialize(codegen_mat_mode())
        B0 = alg.dummy_initialize(codegen_mat_mode(b=True))
        out, _ = alg.fused_spmm(A0, B0, alg.like_s_values(1.0))
        after = store.stats()
        fp = float(np.sum(np.asarray(out, dtype=np.float64) ** 2))
        delta = {k: after[k] - before.get(k, 0) for k in after}
        return alg, store, delta, fp

    def codegen_mat_mode(b=False):
        from distributed_sddmm_tpu.common import MatMode

        return MatMode.B if b else MatMode.A

    alg1, store1, cold, fp_cold = run_plan(plan, store_root)
    keys = [row["key"] for row in store1.index()]
    assert any(f"variant={vid}" in k for k in keys), keys
    _, _, warm, fp_warm = run_plan(plan, store_root)
    assert warm.get("hits", 0) >= 1, warm
    assert warm.get("live_compiles", 0) == 0, warm
    assert fp_warm == fp_cold

    # Generic plan: same fingerprint key, no variant — must MISS (its
    # own compile), never alias the variant's entry.
    plan_generic = Plan(algorithm="15d_fusion2", c=1, kernel="pallas",
                        fingerprint_key="fp-codegen-smoke")
    _, store3, generic_delta, _ = run_plan(plan_generic, store_root)
    assert generic_delta.get("live_compiles", 0) >= 1, generic_delta

    # Stale variant entry: corrupt the payload on disk -> the next
    # process EVICTS and recompiles (never serves the torn entry).
    victim = next(r for r in store1.index() if "variant=" in r["key"])
    store1._path(victim["key"]).write_bytes(b'{"torn": tru')
    _, _, evicted_delta, fp_evict = run_plan(plan, store_root)
    assert evicted_delta.get("live_compiles", 0) >= 1, evicted_delta
    assert fp_evict == fp_cold
    report["store"] = {
        "cold": cold, "warm": warm,
        "generic": generic_delta, "evicted": evicted_delta,
        "variant_keys": sum(1 for k in keys if "variant=" in k),
    }

    # ------------------------------------------------------------------ #
    # 4. Bench record carries the variant + padded-lane metric
    # ------------------------------------------------------------------ #
    S_rec = HostCOO.rmat(log_m=9, edge_factor=4, seed=1)
    rec = benchmark_algorithm(
        S_rec, "15d_fusion2", None, fused=True, R=16, c=1,
        trials=1, warmup=1,
        kernel=codegen.BankedPallasKernel(
            codegen.select_variant(Problem.from_coo(S_rec, R=16)),
            precision="f32", interpret=True,
        ),
    )
    assert rec["kernel_variant"], rec.get("kernel_variant")
    plf = rec["metrics"]["fusedSpMM"].get("padded_lane_frac")
    assert plf is not None and 0.0 <= plf < 1.0, plf
    report["record"] = {
        "kernel_variant": rec["kernel_variant"],
        "padded_lane_frac": plf,
    }

    report["counters"] = {
        k: v for k, v in obs_metrics.GLOBAL.snapshot().items()
        if k.startswith("codegen_")
    }
    assert report["counters"].get("codegen_variants_built", 0) >= 1

    out = json.dumps(report, indent=2, default=str)
    print(out)  # cli-output
    if args.output_file:
        pathlib.Path(args.output_file).write_text(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
