"""Resilient TPU kernel-sweep orchestrator.

The per-chip analog of the reference's `local_kernel_benchmark` sweep
(`/root/reference/local_kernel_benchmark.cpp:276-280`), hardened for the
tunneled TPU backend the same way bench.py is: every (logM, npr, R, kernel)
config runs in its OWN worker subprocess (scripts/tune_blocks.py) under a
hard timeout with process-group kill, failures are retried with backoff,
and finished configs are checkpointed to the output JSONL so a re-run
resumes where it left off.

Usage:
    python scripts/kernel_sweep.py plan.json out.jsonl [--timeout 900]

plan.json: list of {"logM": int, "npr": int, "R": int, "kernel": "xla"|
"pallas", optional "blocks": "BMxBN", "group": int, "fused_only": bool,
"trials": int}.
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import signal
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parents[1]


def config_key(cfg: dict) -> tuple:
    # Defaults must mirror run_worker's env defaults, or an entry without an
    # explicit "blocks" never matches its own output record and re-runs on
    # every resume.
    default_blocks = "512x512" if cfg["kernel"] == "pallas" else ""
    return (
        cfg["logM"], cfg["npr"], cfg["R"], cfg["kernel"],
        cfg.get("blocks", default_blocks), cfg.get("group", 1),
        cfg.get("scatter", "bt") if cfg["kernel"] == "pallas" else "",
        cfg.get("chunk", 128) if cfg["kernel"] == "pallas" else 0,
        bool(cfg.get("batch")) if cfg["kernel"] == "pallas" else False,
    )


def record_key(rec: dict) -> tuple:
    # Prefer the REQUESTED blocks echoed by tune_blocks (blocks_req): the
    # realized bm/bn can differ when pick_block clamps the preference, and
    # keying on the realized pair would re-run such configs forever. Records
    # predating the echo fall back to the realized pair (never clamped in
    # the committed data).
    blocks = rec.get("blocks_req") or (
        f"{rec['bm']}x{rec['bn']}" if "bm" in rec else "")
    is_pallas = rec["kernel"].startswith("pallas")
    return (
        rec["logM"], rec["npr"], rec["R"],
        "pallas" if is_pallas else rec["kernel"],
        blocks, rec.get("group", 1),
        rec.get("scatter_form", "bt") if is_pallas else "",
        rec.get("chunk", 128) if is_pallas else 0,
        bool(rec.get("batch_step")) if is_pallas else False,
    )


def preflight_key(cfg: dict) -> tuple:
    """Kernel-configuration identity used by scripts/preflight_kernels.py
    (grid size excluded — compile validity doesn't depend on logM/npr).
    ``or``-normalized because preflight records carry explicit nulls for
    absent knobs while plan configs simply omit them."""
    return (cfg.get("blocks") or "512x512", cfg.get("group") or 1,
            cfg.get("chunk") or 128, cfg.get("scatter") or "bt",
            bool(cfg.get("batch")), cfg["R"])


def failed_preflight_keys(path: pathlib.Path) -> set:
    """Kernel configs the offline Mosaic AOT check proved uncompilable —
    running them on the chip would only burn the health window on a
    deterministic failure. Only ``compile-error`` counts: a preflight
    timeout or garbled output is not proof the config can't compile."""
    try:
        report = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError):
        return set()
    return {preflight_key(rec) for rec in report.get("configs", [])
            if rec.get("status") == "compile-error"}


def done_keys(out_path: pathlib.Path) -> set:
    keys = set()
    if out_path.exists():
        for line in out_path.read_text().splitlines():
            try:
                keys.add(record_key(json.loads(line)))
            except (json.JSONDecodeError, KeyError):
                continue
    return keys


_AOT_GATE = None


def _aot_gate():
    """Shared AOT-gate policy module, imported from its FILE — the package
    __init__ would pull jax into this backend-free orchestrator."""
    global _AOT_GATE
    if _AOT_GATE is None:
        import importlib.util

        p = REPO / "distributed_sddmm_tpu" / "bench" / "aot_gate.py"
        spec = importlib.util.spec_from_file_location("_aot_gate_file", str(p))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _AOT_GATE = mod
    return _AOT_GATE


def aot_validated(program: str | None = None) -> bool:
    """True when the AOT-load probe recorded that locally compiled
    executables load and produce correct numerics on this backend
    (AOT_LOAD.json, written by scripts/aot_load_probe.py).

    ``program`` gates on one probe program ("pallas_fused"/"xla_matmul") —
    one program's failure must not foreclose AOT mode for the other; with
    no argument, ALL programs must be validated. Policy shared with
    bench.py via aot_gate."""
    if os.environ.get("KERNEL_SWEEP_NO_AOT", "") not in ("", "0"):
        return False
    gate = _aot_gate()
    return gate.probe_validated(
        gate.load_verdict(REPO / "AOT_LOAD.json"), program)


def _aot_code_hash() -> str:
    """Fingerprint of the sources that determine the compiled kernels —
    stale serialized executables must never be timed as current code."""
    import hashlib

    h = hashlib.sha256()
    for rel in ("distributed_sddmm_tpu/ops/pallas_kernels.py",
                "distributed_sddmm_tpu/ops/blocked.py",
                "distributed_sddmm_tpu/bench/aot.py",
                "scripts/tune_blocks.py",
                "scripts/aot_compile_kernels.py"):
        h.update((REPO / rel).read_bytes())
    return h.hexdigest()[:10]


def aot_precompile(cfg: dict, env: dict, timeout_s: float = 420.0) -> str | None:
    """Build this config's serialized chain pairs offline (CPU-pinned
    subprocess, local Mosaic compile — seconds, no tunnel). Returns the
    cache dir to pass as TUNE_LOAD_DIR, or None to use on-device compile.
    The cache key carries fused_only (op set differs) and a source hash
    (old binaries must not masquerade as current kernels)."""
    key = "_".join(str(p) for p in config_key(cfg)).replace("/", "-")
    out_dir = REPO / "artifacts" / "aot_kernels" / (
        key + f"_t{cfg.get('trials', 5)}"
        + f"_f{1 if cfg.get('fused_only') else 0}_{_aot_code_hash()}")
    meta_path = out_dir / "meta.json"
    if meta_path.exists():
        try:
            ok = bool(json.loads(meta_path.read_text()).get("ok"))
        except (OSError, json.JSONDecodeError):
            ok = False
        return str(out_dir) if ok else None
    def tombstone(reason: str) -> None:
        # Negative cache: a deterministic local compile failure must not
        # re-spend its ~420s timeout on every retry of every queue cycle.
        out_dir.mkdir(parents=True, exist_ok=True)
        meta_path.write_text(json.dumps({"ok": False, "error": reason}))

    # Set unconditionally: a stray AOTC_KERNEL in the inherited env must
    # never flip a pallas precompile into the xla branch (or vice versa).
    cenv = dict(env, JAX_PLATFORMS="cpu", AOTC_KERNEL=cfg["kernel"])
    try:
        proc = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "aot_compile_kernels.py"),
             str(cfg["logM"]), str(cfg["npr"]), str(cfg["R"]),
             str(cfg.get("trials", 5)), str(out_dir)],
            env=cenv, capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        # A timeout on a loaded machine is not proof of a deterministic
        # failure (the preflight treats timeouts as non-conclusive).
        # aot_gate.timeout_strike tombstones only after strikes from two
        # INDEPENDENT load episodes (>=30 min apart) — the retry loop's
        # same-spike repeats count as one.
        if _aot_gate().timeout_strike(out_dir):
            tombstone(f"repeated timeouts ({timeout_s:.0f}s budget)")
        print(f"[sweep] AOT precompile timed out for {config_key(cfg)}; "
              "using on-device compile", flush=True)
        return None
    if proc.returncode != 0 or not (out_dir / "meta.json").exists():
        tail = (proc.stderr or "").strip().splitlines()[-3:]
        print(f"[sweep] AOT precompile failed for {config_key(cfg)} "
              f"(rc={proc.returncode}, {tail}); using on-device compile",
              flush=True)
        if proc.returncode >= 0 and not (out_dir / "meta.json").exists():
            # Negative rc = signal kill (OOM etc.) — transient, no tombstone.
            tombstone(f"rc={proc.returncode}: {tail}")
        return None
    return str(out_dir)


def worker_env(cfg: dict) -> dict:
    """The tune_blocks subprocess env for one plan config — also what the
    offline AOT compiler keys its cache on, so prewarm and run must agree."""
    env = dict(os.environ)
    env["PYTHONPATH"] = f"{REPO}:{env.get('PYTHONPATH', '')}"
    if cfg["kernel"] == "xla":
        env["TUNE_BLOCKS"] = "0x0"  # no pallas configs
    else:
        env["TUNE_SKIP_XLA"] = "1"
        env["TUNE_BLOCKS"] = cfg.get("blocks", "512x512")
        env["TUNE_GROUP"] = str(cfg.get("group", 1))
        env["TUNE_SCATTER"] = cfg.get("scatter", "bt")
        env["DSDDMM_CHUNK"] = str(cfg.get("chunk", 128))
        env["TUNE_BATCH"] = "1" if cfg.get("batch") else "0"
        if cfg.get("fused_only"):
            env["TUNE_FUSED_ONLY"] = "1"
    return env


def run_worker(cfg: dict, timeout_s: float) -> list[dict] | None:
    env = worker_env(cfg)
    if aot_validated(_aot_gate().probe_program(cfg["kernel"])):
        load_dir = aot_precompile(cfg, env)
        if load_dir:
            env["TUNE_LOAD_DIR"] = load_dir
    proc = subprocess.Popen(
        [sys.executable, str(REPO / "scripts" / "tune_blocks.py"),
         str(cfg["logM"]), str(cfg["npr"]), str(cfg["R"]),
         str(cfg.get("trials", 5))],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, start_new_session=True,
    )
    try:
        stdout, stderr = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        try:
            proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        print(f"[sweep] {config_key(cfg)}: timeout {timeout_s:.0f}s", flush=True)
        return None
    recs = []
    for line in (stdout or "").splitlines():
        try:
            recs.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    if not recs:
        tail = (stderr or "").strip().splitlines()[-3:]
        print(f"[sweep] {config_key(cfg)}: rc={proc.returncode}, no records; "
              f"stderr tail: {tail}", flush=True)
        return None
    return recs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("plan")
    ap.add_argument("output")
    ap.add_argument("--timeout", type=float, default=900.0,
                    help="per-config hard timeout (seconds)")
    ap.add_argument("--retries", type=int, default=2)
    ap.add_argument("--backoff", type=float, default=45.0)
    ap.add_argument("--kernel-filter", default=None, choices=("xla", "pallas"),
                    help="run only this kernel's configs from the plan")
    ap.add_argument("--preflight", default=str(REPO / "PREFLIGHT.json"),
                    help="offline Mosaic compile report; configs it marks "
                         "failed are skipped (pass an absent path to disable)")
    ap.add_argument("--prewarm", action="store_true",
                    help="only build the offline AOT caches for every "
                         "not-yet-measured config (CPU-pinned, no TPU "
                         "touched) so a healthy window spends zero time "
                         "on local compiles; ignores the AOT-load verdict "
                         "because it runs BEFORE the verdict exists")
    args = ap.parse_args(argv)

    plan = json.loads(pathlib.Path(args.plan).read_text())
    if args.kernel_filter:
        plan = [cfg for cfg in plan if cfg["kernel"] == args.kernel_filter]
    out_path = pathlib.Path(args.output)
    done = done_keys(out_path)
    bad = failed_preflight_keys(pathlib.Path(args.preflight))
    not_done = [cfg for cfg in plan if config_key(cfg) not in done]
    skipped = [cfg for cfg in not_done if cfg["kernel"] == "pallas"
               and preflight_key(cfg) in bad]
    for cfg in skipped:
        print(f"[sweep] skipping {config_key(cfg)}: failed offline Mosaic "
              f"preflight ({args.preflight})", flush=True)

    todo = [cfg for cfg in not_done if cfg not in skipped]
    print(f"[sweep] {len(plan)} planned, {len(plan) - len(not_done)} "
          f"already done, {len(skipped)} preflight-skipped, "
          f"{len(todo)} to run", flush=True)
    if args.prewarm:
        warmed = failures = 0
        # Yield when the verdict file is WRITTEN after we start (the
        # probe re-answering = a healthy window just began); a verdict
        # merely left over from a past window must not no-op prewarm.
        t_start = time.time()

        def _healthy_window_began() -> bool:
            try:
                return (REPO / "AOT_LOAD.json").stat().st_mtime > t_start
            except OSError:
                return False

        for n, cfg in enumerate(todo):
            if _healthy_window_began():
                # Stop competing for the single CPU core with real
                # measurements — the sweep warms remaining caches lazily.
                print("[prewarm] AOT_LOAD.json refreshed; yielding to the "
                      "healthy-tier pipeline", flush=True)
                break
            d = aot_precompile(cfg, worker_env(cfg))
            warmed += d is not None
            failures += d is None
            print(f"[prewarm] {n + 1}/{len(todo)} {config_key(cfg)} "
                  f"{'ok' if d else 'FAILED'}", flush=True)
        print(f"[prewarm] {warmed}/{len(todo)} caches ready", flush=True)
        return 1 if failures else 0
    failures = 0
    for n, cfg in enumerate(todo):
        for attempt in range(1 + args.retries):
            if attempt:
                time.sleep(args.backoff * attempt)
            t0 = time.time()
            recs = run_worker(cfg, args.timeout)
            if recs is not None:
                with out_path.open("a") as f:
                    for rec in recs:
                        f.write(json.dumps(rec) + "\n")
                print(f"[sweep] {n + 1}/{len(todo)} {config_key(cfg)} ok "
                      f"({time.time() - t0:.0f}s)", flush=True)
                break
        else:
            failures += 1
            print(f"[sweep] {config_key(cfg)} FAILED after retries", flush=True)
    print(f"[sweep] complete, {failures} failures", flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
