"""CPU-mesh admin-surface smoke: the live operational endpoints end to end.

Boots one warm ALS fold-in engine with an **ephemeral** admin port
(``AdminServer(port=0)`` — the library face of ``bench serve
--admin-port 0``) on the same virtual 8-device CPU mesh the test suite
uses, then drives real HTTP scrapes through stdlib urllib:

1. **scrape** — ``/metrics`` under open-loop load: every line is
   Prometheus-parseable (text format 0.0.4), the latency histogram's
   cumulative buckets are monotone and agree with ``_count``, counters
   are monotone between two scrapes, and — one scrape after the load
   settles — counter values match the engine's own recorder/stats
   numbers exactly.
2. **health_ready** — ``/healthz`` and ``/readyz`` are 200 while the
   runner is alive, warm, and within SLO budget; ``/debug/requests``
   returns the recent request timelines off the tracer ring.
3. **burn_flip** — the same engine judged by an impossibly tight SLO:
   readiness flips to 503 with ``slo_burn_ok: false`` while liveness
   stays 200 (pull the replica from rotation, don't restart it).
4. **faulted** — an injected **persistent** ``execute:serveBatch``
   fault: the engine degrades every batch to the serial rung but never
   dies — ``/healthz`` stays 200 under the storm and the scrape's
   degraded/retry counters record it.

Usage::

    python scripts/admin_smoke.py [-o out.json]

Prints one JSON summary; exits nonzero if any check fails.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import re
import sys
import time
import urllib.error
import urllib.request

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

#: One Prometheus text-format sample line (comments/blank handled apart).
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})?\s+"
    r"(-?[0-9.]+(?:[eE][-+]?[0-9]+)?|NaN)$"
)


def _get(port: int, path: str):
    """(status, body) — 4xx/5xx are answers here, not exceptions."""
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10
        ) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def parse_metrics(text: str) -> dict:
    """{name or name{labels}: float} for every sample line; raises on a
    line the format forbids — the parseability check IS this parse."""
    out = {}
    for ln, line in enumerate(text.splitlines(), 1):
        if not line or line.startswith("#"):
            continue
        if not _SAMPLE_RE.match(line):
            raise ValueError(f"line {ln} not Prometheus-parseable: {line!r}")
        key, val = line.rsplit(None, 1)
        out[key] = float(val)
    return out


def _build(seed: int = 0):
    from distributed_sddmm_tpu.models.als import DistributedALS
    from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
    from distributed_sddmm_tpu.serve import ALSFoldInTopK, ServingEngine
    from distributed_sddmm_tpu.utils.coo import HostCOO

    S = HostCOO.erdos_renyi(64, 48, 6, seed=seed, values="normal")
    alg = DenseShift15D(S, R=8, c=1, fusion_approach=2)
    model = DistributedALS(alg, S_host=S)
    model.run_cg(2, cg_iters=4)
    workload = ALSFoldInTopK(model, k=5, item_buckets=(4, 8))
    engine = ServingEngine(
        workload, max_batch=4, max_depth=32, max_wait_ms=2.0
    )
    return model, workload, engine


def check_scrape(model, engine, port) -> dict:
    from distributed_sddmm_tpu.serve import run_load

    first = parse_metrics(_get(port, "/metrics")[1])
    run_load(engine, duration_s=1.2, rate_hz=30, seed=2, oracle_every=0)
    mid = parse_metrics(_get(port, "/metrics")[1])
    # One scrape interval after the load drains, the surface and the
    # engine's own accounting must agree exactly.
    time.sleep(0.2)
    final = parse_metrics(_get(port, "/metrics")[1])
    summary = engine.recorder.summary()
    stats = engine.stats()

    monotone = all(
        final.get(k, 0.0) >= v
        for k, v in mid.items()
        if k.endswith("_total") or "_bucket" in k or k.endswith("_count")
    ) and all(mid.get(k, 0.0) >= v for k, v in first.items()
              if k.endswith("_total"))
    buckets = [
        (k, v) for k, v in final.items()
        if k.startswith("dsddmm_request_latency_ms_bucket")
    ]
    cum = [v for _, v in buckets]
    hist_ok = (
        cum == sorted(cum)
        and cum
        and cum[-1] == final.get("dsddmm_request_latency_ms_count")
    )
    matches = {
        "dsddmm_requests_completed_total": summary["completed"],
        "dsddmm_requests_shed_total": summary["shed_count"],
        "dsddmm_requests_errors_total": summary["errors"],
        "dsddmm_served_requests_total": stats["served"],
        "dsddmm_program_cache_misses_total": stats["cache_misses"],
        "dsddmm_request_latency_ms_count": summary["completed"],
    }
    agree = {k: final.get(k) == float(v) for k, v in matches.items()}
    return {
        "name": "scrape",
        "ok": bool(
            monotone and hist_ok and all(agree.values())
            and summary["completed"] > 0
        ),
        "completed": summary["completed"],
        "monotone": monotone,
        "hist_cumulative_ok": hist_ok,
        "agree": agree,
        "families": len(final),
    }


def check_health_ready(engine, port) -> dict:
    h_code, _ = _get(port, "/healthz")
    r_code, r_body = _get(port, "/readyz")
    d_code, d_body = _get(port, "/debug/requests")
    dbg = json.loads(d_body)
    ready = json.loads(r_body)
    return {
        "name": "health_ready",
        "ok": bool(
            h_code == 200 and r_code == 200 and ready["ready"]
            and ready["checks"]["warm"] and d_code == 200
            and dbg["complete"] > 0 and dbg["requests"]
        ),
        "healthz": h_code,
        "readyz": r_code,
        "debug_complete_chains": dbg["complete"],
    }


def check_burn_flip(model, engine) -> dict:
    from distributed_sddmm_tpu.obs import httpexp
    from distributed_sddmm_tpu.serve import SLOSpec

    tight = httpexp.AdminServer(
        engine=engine, op_metrics=model.d_ops.metrics,
        slo=SLOSpec.parse("p99_ms=0.0001"), port=0,
    )
    tight.start()
    try:
        r_code, r_body = _get(tight.port, "/readyz")
        h_code, _ = _get(tight.port, "/healthz")
        m = parse_metrics(_get(tight.port, "/metrics")[1])
        ready = json.loads(r_body)
    finally:
        tight.stop()
    burn = m.get("dsddmm_slo_burn_rate")
    return {
        "name": "burn_flip",
        "ok": bool(
            r_code == 503 and not ready["ready"]
            and ready["checks"]["slo_burn_ok"] is False
            and h_code == 200  # liveness unaffected: drain, don't restart
            and burn is not None and burn > 1.0
        ),
        "readyz": r_code,
        "healthz": h_code,
        "burn_rate": burn,
    }


def check_faulted(engine, port) -> dict:
    from distributed_sddmm_tpu.resilience import (
        FaultPlan, FaultSpec, fault_plan,
    )
    from distributed_sddmm_tpu.serve import run_load

    before = parse_metrics(_get(port, "/metrics")[1])
    plan = FaultPlan([
        FaultSpec(site="execute:serveBatch", kind="error", prob=1.0),
    ])
    with fault_plan(plan):
        summary = run_load(
            engine, duration_s=1.0, rate_hz=20, seed=5, oracle_every=4
        )
    h_code, _ = _get(port, "/metrics")  # scrape survives the storm
    alive_code, _ = _get(port, "/healthz")
    after = parse_metrics(_get(port, "/metrics")[1])
    degraded_delta = (
        after.get("dsddmm_requests_degraded_total", 0)
        - before.get("dsddmm_requests_degraded_total", 0)
    )
    stats = engine.stats()
    return {
        "name": "faulted",
        "ok": bool(
            alive_code == 200 and h_code == 200
            and summary["oracle_failures"] == 0
            and degraded_delta > 0
            and after.get("dsddmm_requests_degraded_total")
            == float(summary["degraded_count"])
            and after.get("dsddmm_degraded_batches_total")
            == float(stats["degraded_batches"])
        ),
        "healthz_under_fault": alive_code,
        "degraded_delta": degraded_delta,
        "faults_fired": len(plan.events),
        "oracle_failures": summary["oracle_failures"],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-o", "--output-file", default=None)
    args = ap.parse_args(argv)

    from distributed_sddmm_tpu.utils.platform import force_cpu_platform

    force_cpu_platform(n_devices=8, replace=True)

    from distributed_sddmm_tpu.obs import httpexp
    from distributed_sddmm_tpu.serve import SLOSpec

    t0 = time.perf_counter()
    model, workload, engine = _build()
    admin = httpexp.AdminServer(
        engine=engine, op_metrics=model.d_ops.metrics,
        slo=SLOSpec.parse("p99_ms=60000,err_rate=0.9"),  # loose: stays ready
        port=0,  # ephemeral — the bench serve --admin-port 0 contract
    )
    admin.start()
    engine.start()
    try:
        checks = [check_scrape(model, engine, admin.port)]
        checks.append(check_health_ready(engine, admin.port))
        checks.append(check_burn_flip(model, engine))
        checks.append(check_faulted(engine, admin.port))
    finally:
        engine.stop()
        admin.stop()

    report = {
        "ok": all(c["ok"] for c in checks),
        "elapsed_s": round(time.perf_counter() - t0, 2),
        "admin_port": admin.port,
        "checks": checks,
    }
    text = json.dumps(report, indent=1)
    print(text)
    if args.output_file:
        pathlib.Path(args.output_file).write_text(text)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
