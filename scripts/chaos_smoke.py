"""Gray-failure chaos drill: every injected fault detected, zero wrong bytes.

The PR-17 acceptance demo on the CPU test mesh (a tier-1 test runs this
as a subprocess): ``bench fleet`` under a seeded four-fault chaos
schedule — a **wedge** (SIGSTOP: alive, holds its ports, answers
nothing), a **partition** (wire drops while health lies), a **corrupt**
(finite-but-wrong output bytes behind repair-mode guards — only the
cross-replica audit can see them), and a **kill** — and the judgment
must hold:

* every gray fault is *detected* within the deadline: wedge/partition
  by a circuit-breaker open on the victim, corrupt by a byzantine
  quarantine verdict (``detection_ok`` in the record);
* every delivered 200 reply is bit-identical to the single-engine
  oracle — the byzantine replica leaks nothing past the pre-delivery
  audit (``mismatches == 0`` while ``audit_mismatches > 0``: the
  detector FIRED and the client never saw it);
* nothing lost, warm respawns only, availability above the floor —
  the PR-16 contract holds under a much nastier schedule;
* the same seed reproduces the same timeline: the recorded chaos
  events replay the schedule this script re-derives locally;
* (PR 19) fleet-wide tracing rides along: the run's harvested trace
  shards merge into one causal tree, every DELIVERED reply
  reconstructs a complete router→attempt→replica chain whose winning
  span agrees with the router's recorded latency within 1 ms
  (``fleet.trace.coverage == 1.0``), and ``bench report-trace`` holds
  its 0/2 exit contract on the merged trace.

Usage::

    python scripts/chaos_smoke.py [-o out.json]

Prints one JSON report; exit 0 when every check passes, 2 otherwise
(the 0/2 contract ``tests/test_chaos_smoke.py`` pins).
"""

from __future__ import annotations

import argparse
import contextlib
import io
import json
import os
import pathlib
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

#: The drill: fractions of a 10 s load window. Spread so each fault's
#: detector has a healthy quorum when it matters — the corrupt fires
#: last, after the wedge and partition victims have recovered, so the
#: byzantine arbitration always has a tiebreak replica.
CHAOS_SPEC = ("wedge:r0@0.15/3.5s;partition:r2@0.45/1s;"
              "corrupt:r1@0.8;kill@0.9")
SEED = 7
DURATION_S = 10.0
AVAILABILITY_FLOOR = 0.9


def exit_code(report: dict) -> int:
    """The smoke's exit contract: 0 all checks green, 2 otherwise."""
    return 0 if report.get("ok") else 2


def check_chaos_drill(tmp: pathlib.Path) -> dict:
    """One four-fault ``bench fleet`` drill, then re-judge the record."""
    from distributed_sddmm_tpu.bench.cli import main as bench_main
    from distributed_sddmm_tpu.obs.regress import phase_stats
    from distributed_sddmm_tpu.resilience.chaos import ChaosSchedule

    # Arm fleet-wide tracing for the drill (the tier-1 test scrubs the
    # environment, so the knob must be set HERE): router + replicas
    # shard into the path's sibling dir, the run merges them and
    # records chain coverage in the fleet record.
    os.environ["DSDDMM_FLEET_TRACE"] = str(tmp / "fleet_trace.jsonl")

    out = tmp / "chaos.json"
    rc = bench_main([
        "fleet", "--replicas", "3", "--chaos", CHAOS_SPEC,
        "--seed", str(SEED), "--duration", str(DURATION_S),
        "--rate", "8", "--log-m", "6", "--R", "8", "--hedge", "on",
        "--detect-deadline", "5",
        "--availability-floor", str(AVAILABILITY_FLOOR),
        "--no-runstore", "-o", str(out),
    ])
    records = [json.loads(line) for line in out.read_text().splitlines()]
    rec = records[-1] if records else {}
    fleet = rec.get("fleet") or {}
    axes = phase_stats({"record": rec})

    # Seeded determinism: the schedule this script derives locally must
    # be the timeline the run actually fired (kind order + planned
    # times; targets where the spec names one).
    schedule = ChaosSchedule.parse(CHAOS_SPEC, seed=SEED)
    planned = schedule.timeline(DURATION_S)
    fired = fleet.get("chaos_events") or []
    timeline_ok = (
        len(planned) == len(fired)
        # Same kinds at the same planned times, in the same order; an
        # explicitly-targeted action hit the replica the spec named
        # (the kill's target is a runtime seeded pick over the live
        # pool, so only spec-named targets are re-derivable here).
        and all(
            row["kind"] == ev["kind"]
            and row["t_s"] == ev["planned_t_s"]
            and (row["target"] is None or row["target"] == ev["target"])
            for row, ev in zip(planned, fired)
        )
        and fleet.get("chaos") == schedule.normalized
        and fleet.get("chaos_seed") == SEED
    )

    # PR-19 tracing leg: the merged trace explains every delivered
    # reply — one complete cross-process chain each, the winning
    # attempt's span agreeing with the router's recorded latency
    # within 1 ms — and `report-trace` keeps its 0/2 exit contract
    # (0 on the schema-valid merged trace, 2 on a violated copy).
    trace_info = fleet.get("trace") or {}
    merged_path = trace_info.get("merged_path")
    rc_report = rc_bad = None
    report_text = ""
    if merged_path:
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc_report = bench_main(["report-trace", str(merged_path)])
        report_text = buf.getvalue()
        bad = tmp / "violated_trace.jsonl"
        bad.write_text(pathlib.Path(merged_path).read_text()
                       + '{"type": "span", "name": "torn"}\n')
        with contextlib.redirect_stdout(io.StringIO()), \
                contextlib.redirect_stderr(io.StringIO()):
            rc_bad = bench_main(["report-trace", str(bad)])
    trace_ok = bool(
        trace_info.get("coverage") == 1.0
        and (trace_info.get("delivered") or 0) > 0
        and trace_info.get("complete") == trace_info.get("delivered")
        # Router shard + one per replica (at least the three seeds).
        and (trace_info.get("shards") or 0) >= 3
        and (trace_info.get("fleet_links") or 0) > 0
        and rc_report == 0
        and rc_bad == 2
        and "fleet:" in report_text
        # The zero-tolerance coverage axis is derived from the record.
        and "fleet:trace_coverage" in axes
    )

    detection = fleet.get("detection") or []
    return {
        "name": "chaos-drill",
        "ok": bool(
            rc == 0
            # Zero wrong bytes WHILE the byzantine detector fired: the
            # audit saw the corruption and the client never did.
            and fleet.get("mismatches") == 0
            and fleet.get("lost") == 0
            and (fleet.get("audit_mismatches") or 0) > 0
            and (fleet.get("quarantines") or 0) >= 1
            and (fleet.get("breaker_opens") or 0) >= 2
            and (fleet.get("audits") or 0) > 0
            and (fleet.get("hedges") or 0) >= 1
            # Every gray fault detected within the deadline.
            and fleet.get("detection_ok") is True
            and len(detection) == 3
            and {d["kind"] for d in detection}
            == {"wedge", "partition", "corrupt"}
            # The crash fault fired and healed warm.
            and fleet.get("killed")
            and (fleet.get("losses") or 0) >= 1
            and fleet.get("replacement_live_compiles") == 0
            and (fleet.get("replacement_disk_hits") or 0) > 0
            and fleet.get("availability", 0.0) >= AVAILABILITY_FLOOR
            and timeline_ok
            # The gate reads the drill: the zero-tolerance audit axis
            # and the hedge telemetry are derived record phases.
            and "fleet:audit_mismatch" in axes
            and "fleet:availability" in axes
            and trace_ok
        ),
        "exit_code": rc,
        "chaos": fleet.get("chaos"),
        "timeline_ok": timeline_ok,
        "offered": fleet.get("offered"),
        "ok_replies": fleet.get("ok"),
        "mismatches": fleet.get("mismatches"),
        "lost": fleet.get("lost"),
        "audit_mismatches": fleet.get("audit_mismatches"),
        "audits": fleet.get("audits"),
        "quarantines": fleet.get("quarantines"),
        "breaker_opens": fleet.get("breaker_opens"),
        "hedges": fleet.get("hedges"),
        "hedge_wins": fleet.get("hedge_wins"),
        "detection": detection,
        "detection_ok": fleet.get("detection_ok"),
        "killed": fleet.get("killed"),
        "availability": fleet.get("availability"),
        "replacement_live_compiles": fleet.get("replacement_live_compiles"),
        "trace_ok": trace_ok,
        "trace_coverage": trace_info.get("coverage"),
        "trace_delivered": trace_info.get("delivered"),
        "trace_shards": trace_info.get("shards"),
        "trace_fleet_links": trace_info.get("fleet_links"),
        "report_trace_exit": rc_report,
        "report_trace_bad_exit": rc_bad,
        "gate_axes": sorted(k for k in axes if k.startswith("fleet:")),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-o", "--output-file", default=None)
    args = ap.parse_args(argv)

    from distributed_sddmm_tpu.utils.platform import force_cpu_platform

    force_cpu_platform(n_devices=8, replace=True)
    # Two strikes open a breaker: the wedge victim must trip from poll
    # strikes alone inside its window, and the partition victim from
    # audit-probe drops inside its 1 s window.
    os.environ["DSDDMM_FLEET_BREAKER_ERRS"] = "2"

    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory() as tmpdir:
        checks = [check_chaos_drill(pathlib.Path(tmpdir))]

    report = {
        "ok": all(c["ok"] for c in checks),
        "elapsed_s": round(time.perf_counter() - t0, 2),
        "checks": checks,
    }
    text = json.dumps(report, indent=1)
    print(text)
    if args.output_file:
        pathlib.Path(args.output_file).write_text(text)
    return exit_code(report)


if __name__ == "__main__":
    sys.exit(main())
