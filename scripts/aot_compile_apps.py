"""Offline AOT compiler for the application benchmarks' strategy programs.

The apps tier (`scripts/tpu_apps.py`) pays an on-device Mosaic compile for
every distinct strategy program it touches: 6 for ALS (sddmm/spmm/fused,
both orientations), 1 per heatmap R value. This script builds those
executables locally against a v5e topology device — the run_pallas.py
retarget pattern — so the TPU process can `inject_program` them and spend
the health window measuring (GAT is excluded: its per-layer feature widths
retrace, and the injection wrapper's jit fallback covers it anyway).

CPU-pinned. The DenseShift15D arg orders below mirror its public op
methods (`dense_shift_15d.py` sddmm_a/spmm_a/fused_spmm); only the
15d_fusion2 configuration appears in the apps plan.

Usage: python scripts/aot_compile_apps.py APP logM npr R OUT_DIR
(APP in {als, vanilla}; kernel knobs via the usual DSDDMM_* env.)
"""

from __future__ import annotations

import json
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

import jax

jax.config.update("jax_platforms", "cpu")

from jax.experimental import topologies

TOPOLOGY = "v5e:2x4"

from distributed_sddmm_tpu.bench.aot import APP_PROGRAM_KEYS as APP_KEYS  # noqa: E402


def main() -> int:
    app = sys.argv[1]
    log_m, npr, R = (int(x) for x in sys.argv[2:5])
    out_dir = pathlib.Path(sys.argv[5])
    if app not in APP_KEYS:
        print(f"unsupported app {app!r} (want {sorted(APP_KEYS)})",
              file=sys.stderr)
        return 1

    from distributed_sddmm_tpu.bench import aot
    from distributed_sddmm_tpu.common import MatMode
    from distributed_sddmm_tpu.ops.pallas_kernels import PallasKernel
    from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
    from distributed_sddmm_tpu.parallel.mesh import make_grid
    from distributed_sddmm_tpu.utils.coo import HostCOO

    S = HostCOO.rmat(log_m=log_m, edge_factor=npr, seed=0)
    kern = PallasKernel(precision="bf16", interpret=False)
    alg = DenseShift15D(S, R=R, c=1, fusion_approach=2, kernel=kern,
                        devices=jax.devices("cpu")[:1])
    A = alg.dummy_initialize(MatMode.A)
    B = alg.dummy_initialize(MatMode.B)
    targs_s = alg._tile_args(alg.S_tiles, alg.like_s_values(1.0))
    targs_st = alg._tile_args(alg.ST_tiles, alg.like_st_values(1.0))
    # Dense-arg order per (op, use_st), mirroring the public methods.
    call_args = {
        ("sddmm", False): (A, B) + targs_s,
        ("sddmm", True): (B, A) + targs_st,
        ("spmm", False): (B,) + targs_s,
        ("spmm", True): (A,) + targs_st,
        ("fused", False): (A, B) + targs_s,
        ("fused", True): (B, A) + targs_st,
    }

    topo = topologies.get_topology_desc(platform="tpu", topology_name=TOPOLOGY)
    g = alg.grid
    alg.grid = make_grid(g.nr, g.nc, g.nh, adjacency=g.adjacency,
                         devices=[topo.devices[0]])
    alg._programs.clear()
    mesh = alg.grid.mesh

    def sds_like(x):
        sharding = jax.sharding.NamedSharding(mesh, x.sharding.spec)
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)

    report = {"ok": True, "app": app, "compile_s": {}}
    for op, use_st in APP_KEYS[app]:
        prog = alg._program(op, use_st)
        arg_sds = tuple(sds_like(x) for x in call_args[(op, use_st)])
        t0 = time.monotonic()
        compiled = prog.lower(*arg_sds).compile()
        name = f"{op}_{'b' if use_st else 'a'}"
        # Target platform (the topology chip), not the CPU-pinned
        # process backend — the on-chip loader's backend gate must match.
        aot.save_executable(compiled, out_dir, name, 0,
                            backend=topo.devices[0].platform)
        report["compile_s"][name] = round(time.monotonic() - t0, 2)
    (out_dir / "meta.json").write_text(json.dumps(report, indent=1))
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
