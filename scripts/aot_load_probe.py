"""Probe: can locally AOT-compiled TPU executables LOAD on the tunneled chip?

On-device compiles through this environment's tunneled backend cost 2-12
minutes per distinct Pallas program (remote Mosaic service), which is the
binding constraint on every TPU measurement campaign. But the Mosaic/TPU
compiler runs locally against a `jax.experimental.topologies` AOT target
(PREFLIGHT.json proves 22 configs in ~4s each). This probe tests the
missing link: serialize a locally AOT-compiled executable
(`jax.experimental.serialize_executable`) and deserialize_and_load it onto
the real tunneled device, re-homed via ``execution_devices``.

If the answer is yes, sweep compiles move off the chip entirely and a
health window spends its minutes measuring instead of compiling.

Two phases, each its own subprocess (the AOT phase must run with
JAX_PLATFORMS=cpu so the tunneled backend never initializes there):

  A (offline): AOT-compile a Pallas fused-tile chain + an XLA matmul chain
     for one v5e topology device; serialize both + their arg pytrees.
  B (needs the tunnel): load both onto the real chip, run, compare
     numerics against the interpreter oracle, time load vs on-device
     compile of the same program.

Usage: python scripts/aot_load_probe.py [--phase a|b|both] [-o AOT_LOAD.json]
Phase B exits 2 (retryable) when the backend is unreachable.
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import pickle
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

CACHE = REPO / "artifacts" / "aot_cache"
TOPOLOGY = "v5e:2x4"
# Small on purpose: the probe answers "does a re-homed executable LOAD and
# produce correct numerics", not a perf question, and phase A replays the
# Pallas chain in the interpreter for the oracle fingerprints.
LOG_M, NPR, R, TRIALS = 10, 8, 128, 3
# bf16 TPU kernel vs f32 interpreter oracle: bf16 rounding bounds the
# relative fingerprint error; f32-vs-f32 matmul differs only by
# accumulation order.
RTOL = {"pallas_fused": 2e-2, "xla_matmul": 1e-3}
# Per-program chain versions live in aot_gate (the shared gate-policy
# module) so the gates and this probe can never disagree about which
# verdicts are current. File import: the package __init__ would pull jax
# into the light --check-stale path the queue runs every cycle.
def _load_aot_gate():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_aot_gate_file",
        str(REPO / "distributed_sddmm_tpu" / "bench" / "aot_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


PROGRAM_VERSIONS = _load_aot_gate().PROGRAM_VERSIONS
# Identity of the cached phase-A outputs; any program change re-builds.
# JSON-normalized (lists, not tuples): cache_is_fresh compares against the
# json round-trip of this value, and ["a", 1] != ("a", 1) in Python —
# tuples here would make the cache permanently "stale".
PROBE_VERSION = max(PROGRAM_VERSIONS.values())
PROBE_KEY = [TOPOLOGY, LOG_M, NPR, R, TRIALS,
             [[n, v] for n, v in sorted(PROGRAM_VERSIONS.items())]]


def check_stale(out_path: pathlib.Path) -> int:
    """Decide whether the recorded verdict still answers the current
    probe programs. Exit 0 = current and complete (no re-probe needed);
    exit 3 = the probe should (re-)run. Stale program entries are pruned
    in place so still-valid siblings keep gating their own AOT modes."""
    if not out_path.exists():
        return 3
    try:
        rep = json.loads(out_path.read_text())
    except (OSError, json.JSONDecodeError):
        out_path.unlink(missing_ok=True)
        return 3
    if rep.get("stage") == "phase-a":
        # Local deterministic failure record: stands only while EVERY
        # program chain is unchanged (a scalar max() would miss a bump
        # that doesn't raise the max).
        if rep.get("program_versions") == PROGRAM_VERSIONS:
            return 0
        out_path.unlink()
        return 3
    progs = rep.get("programs") or {}
    # Entries written before per-program versioning carry no
    # program_version; those chains were version 1, so default to 1 —
    # a still-chain-valid verdict must survive a sibling's bump.
    pruned = {n: e for n, e in progs.items()
              if e.get("program_version", 1) == PROGRAM_VERSIONS.get(n)}
    if set(pruned) == set(PROGRAM_VERSIONS):
        return 0
    if not pruned:
        out_path.unlink()
        return 3
    if pruned != progs:
        rep["programs"] = pruned
        rep["ok"] = False  # a program's verdict is now missing
        out_path.write_text(json.dumps(rep, indent=1))
        print(f"[aot-probe] pruned stale program verdicts; kept "
              f"{sorted(pruned)}", file=sys.stderr)
    return 3


def cache_is_fresh() -> bool:
    try:
        meta = json.loads((CACHE / "meta.json").read_text())
    except (OSError, json.JSONDecodeError):
        return False
    if meta.get("probe_key") != list(PROBE_KEY):
        return False
    names = [f"{k}_{n}.pkl" for k in ("pallas_fused", "xla_matmul")
             for n in (1, 1 + TRIALS)]
    return all((CACHE / f).exists() for f in names)


def build_programs():
    """The two chained-trial programs the sweep would time, plus concrete
    host inputs and the interpreter-oracle fingerprint."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from functools import partial

    from distributed_sddmm_tpu.ops.blocked import CHUNK, build_blocked
    from distributed_sddmm_tpu.ops.pallas_kernels import BlockedTile, PallasKernel
    from distributed_sddmm_tpu.utils.coo import HostCOO

    S = HostCOO.rmat(log_m=LOG_M, edge_factor=NPR, seed=0)
    S = S.with_values(np.random.default_rng(1).standard_normal(S.nnz))
    rng = np.random.default_rng(0)
    A_h = rng.standard_normal((S.M, R)).astype(np.float32)
    B_h = rng.standard_normal((S.N, R)).astype(np.float32)
    meta = build_blocked(1, np.zeros(S.nnz, np.int64), S.rows, S.cols,
                         S.M, S.N, block_rows=512, block_cols=512, group=4)
    vals_np = np.zeros(meta.n_chunks * CHUNK, np.float32)
    vals_np[meta.host_to_chunk] = S.vals

    def make_chain(kern_kwargs):
        kern = PallasKernel(**{"precision": "bf16", **kern_kwargs})

        def step(state):
            # acc accumulates the RAW kernel output so the fingerprint is
            # fully sensitive to it (the 1e-12-scaled feedback into Bs
            # exists only to chain the steps data-dependently; on its own
            # it would let a garbage kernel still "match" sum(B)).
            acc, Bs, lr, lc, m, cv, a = state
            blk = BlockedTile(lr=lr, lc=lc, meta=m, bm=meta.bm, bn=meta.bn,
                              gr_blocks=meta.gr_blocks,
                              gc_blocks=meta.gc_blocks, group=meta.group)
            o, _mid = kern.fused_tile(blk, cv, a, Bs)
            return (acc + o[: S.N], Bs + o[: S.N] * 1e-12, lr, lc, m, cv, a)

        def chain_n(n):
            # Trip count closed over (not static_argnums): the serialized
            # executable then has a plain array-only calling convention.
            @jax.jit
            def chain(state):
                return jax.lax.fori_loop(0, n, lambda _, s: step(s), state)
            return chain

        return chain_n

    def make_xla_chain():
        def chain_n(n):
            @jax.jit
            def chain(state):
                def body(_, s):
                    x, w = s
                    # HIGHEST keeps the TPU matmul in f32 passes: the CPU
                    # oracle is f32, and the default TPU precision (bf16
                    # passes) can exceed the 1e-3 fingerprint rtol — a
                    # numerics "mismatch" that would conclusively (and
                    # wrongly) record ok:false and foreclose AOT mode.
                    y = jnp.matmul(x, w, precision=jax.lax.Precision.HIGHEST)
                    return (jnp.tanh(y), w)
                return jax.lax.fori_loop(0, n, body, state)
            return chain
        return chain_n

    state = (np.zeros_like(B_h), B_h, np.asarray(meta.lr[0]),
             np.asarray(meta.lc[0]), np.asarray(meta.meta[0]), vals_np, A_h)
    xla_state = (A_h[:1024, :], A_h[: R, : R])
    return make_chain, make_xla_chain, state, xla_state


def phase_a() -> None:
    """AOT-compile + serialize against one topology device (offline)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from jax.experimental import topologies
    from jax.experimental import serialize_executable as se

    make_chain, make_xla_chain, state, xla_state = build_programs()
    topo = topologies.get_topology_desc(platform="tpu", topology_name=TOPOLOGY)
    dev = topo.devices[0]
    sharding = jax.sharding.SingleDeviceSharding(dev)

    def sds_of(x):
        import numpy as np
        x = np.asarray(x)
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)

    CACHE.mkdir(parents=True, exist_ok=True)
    records = {"probe_key": list(PROBE_KEY)}
    for name, chain_n, oracle_chain_n, st in (
        ("pallas_fused", make_chain({"interpret": False}),
         make_chain({"interpret": True, "precision": "f32"}), state),
        ("xla_matmul", make_xla_chain(), make_xla_chain(), xla_state),
    ):
        for n in (1, 1 + TRIALS):
            t0 = time.monotonic()
            compiled = chain_n(n).lower(
                tuple(sds_of(x) for x in st)).compile()
            payload = se.serialize(compiled)
            (CACHE / f"{name}_{n}.pkl").write_bytes(pickle.dumps(payload))
            # Ground-truth fingerprint from the interpreter/CPU execution
            # of the same chain — phase B must reproduce it or the load
            # does not count as working.
            import numpy as np
            ref = oracle_chain_n(n)(tuple(np.asarray(x) for x in st))
            records[f"{name}_{n}"] = {
                "compile_s": round(time.monotonic() - t0, 2),
                "bytes": (CACHE / f"{name}_{n}.pkl").stat().st_size,
                "oracle_fp": float(np.asarray(ref[0], np.float64).sum()),
            }
    (CACHE / "meta.json").write_text(json.dumps(records, indent=1))
    # Fresh programs get a fresh exception budget in phase B.
    (CACHE / "phase_b_attempts").unlink(missing_ok=True)
    print(json.dumps({"phase": "a", "ok": True, **records}))


def conclusive_error(msg: str) -> bool:
    """Exception text that proves re-homing can NEVER work here, as opposed
    to a tunnel flake. A deserialize-format version mismatch ("cached
    executable is axon format vX, this build is vY") is a property of the
    (local serializer, tunnel build) pair: an executable serialized by this
    libtpu can never load on this backend build, so the "no" is recorded
    immediately instead of burning two more health-window cycles on the
    exception retry budget. The match is the specific version-mismatch
    phrase — a generic deserialize failure (e.g. a payload truncated by a
    flaky tunnel) must stay retryable. Policy-home note: belongs in
    bench/aot_gate.py with the other permanence rules; moving it edits a
    bench-code_hash-covered file, so it rides the next batched package
    edit (one that is anyway followed by a headline re-bank)."""
    return ("PJRT_Executable_DeserializeAndLoad" in msg
            and " format v" in msg and "this build is" in msg)


def _settled(entry: dict) -> bool:
    """An entry that answers its program's question for good: a success,
    a numerics verdict, or a conclusive (deterministic) error. Retryable
    tunnel flakes are not settled."""
    return "error" not in entry or conclusive_error(entry["error"])


def _merge_write(out_path: pathlib.Path, report: dict,
                 new_programs: dict) -> dict:
    """Write ``new_programs`` over the still-chain-current entries already
    on disk. A transient outcome (sibling flake, retry pass) must never
    clobber a settled recorded verdict — the same guard PREFLIGHT.json
    applies to its ok records. Returns the merged report as written."""
    try:
        prior = json.loads(out_path.read_text()).get("programs") or {}
    except (OSError, json.JSONDecodeError, AttributeError):
        prior = {}
    progs = {}
    for n in set(prior) | set(new_programs):
        pe, ne = prior.get(n), new_programs.get(n)
        prior_settled = (
            pe is not None
            and pe.get("program_version", 1) == PROGRAM_VERSIONS.get(n)
            and _settled(pe))
        if ne is not None and _settled(ne):
            progs[n] = ne  # a fresh settled verdict always wins
        elif prior_settled:
            progs[n] = pe
        elif ne is not None:
            progs[n] = ne  # both unsettled: record the freshest attempt
        # prior chain-stale entries drop here (check_stale would prune)
    merged = dict(report, programs=progs)
    merged["ok"] = (set(progs) >= set(PROGRAM_VERSIONS)
                    and all(p.get("ok") for p in progs.values()))
    out_path.write_text(json.dumps(merged, indent=1))
    return merged


def phase_b() -> int:
    """Load the serialized executables onto the real tunneled chip.

    Returns 0 (every program's answer recorded, good or bad) or 2
    (retryable: backend unreachable, or some program hit a transient
    exception with retry budget left — settled sibling verdicts ARE
    merge-recorded before returning, so their gates stop waiting)."""
    import numpy as np
    import jax

    from jax.experimental import serialize_executable as se

    try:
        dev = jax.devices()[0]
        if dev.platform == "cpu":
            print("[aot-probe] no TPU backend (cpu only) — retry later",
                  file=sys.stderr)
            return 2
    except Exception as e:  # noqa: BLE001 — backend init is the flaky part
        print(f"[aot-probe] backend init failed (retryable): {e}",
              file=sys.stderr)
        return 2

    meta = json.loads((CACHE / "meta.json").read_text())
    report = {"phase": "b", "probe_version": PROBE_VERSION,
              "platform": dev.platform,
              "device": str(dev), "n_devices": jax.device_count(),
              "programs": {}}
    make_chain, make_xla_chain, state, xla_state = build_programs()

    for name, st in (("pallas_fused", state), ("xla_matmul", xla_state)):
        entry = {"program_version": PROGRAM_VERSIONS[name]}
        try:
            dev_state = tuple(jax.device_put(np.asarray(x), dev) for x in st)
            fp_ok = []
            for n in (1, 1 + TRIALS):
                payload = pickle.loads((CACHE / f"{name}_{n}.pkl").read_bytes())
                serialized, in_tree, out_tree = payload
                t0 = time.monotonic()
                loaded = se.deserialize_and_load(
                    serialized, in_tree, out_tree, backend=dev.client,
                    execution_devices=[dev])
                entry[f"load_s_n{n}"] = round(time.monotonic() - t0, 3)
                t0 = time.monotonic()
                out = loaded(dev_state)
                # Host fetch forces execution on the tunneled backend.
                fp = float(np.asarray(out[0], np.float64).sum())
                entry[f"first_run_s_n{n}"] = round(time.monotonic() - t0, 3)
                oracle = meta[f"{name}_{n}"]["oracle_fp"]
                entry[f"run_fp_n{n}"] = fp
                entry[f"oracle_fp_n{n}"] = oracle
                fp_ok.append(
                    abs(fp - oracle) <= RTOL[name] * max(abs(oracle), 1.0))
            entry["numerics_ok"] = all(fp_ok)
            entry["ok"] = entry["numerics_ok"]
        except Exception as e:  # noqa: BLE001 — probe records any failure mode
            entry["ok"] = False
            entry["error"] = f"{type(e).__name__}: {e}"[:500]
        report["programs"][name] = entry

    report["ok"] = all(p.get("ok") for p in report["programs"].values())
    out_path = pathlib.Path(
        os.environ.get("AOT_LOAD_OUT", str(REPO / "AOT_LOAD.json")))
    # An exception mid-phase is ambiguous: a genuine re-homing
    # incompatibility OR a tunnel flake after the init check. Don't let
    # one flake permanently foreclose AOT mode — only record a "no" once
    # exceptions have repeated enough to be deterministic (numerics
    # mismatches and conclusive_error texts, by contrast, are conclusive
    # immediately).
    unsettled = {n: e for n, e in report["programs"].items()
                 if not _settled(e)}
    if unsettled:
        attempts_file = CACHE / "phase_b_attempts"
        try:
            attempts = int(attempts_file.read_text()) + 1
        except (OSError, ValueError):
            attempts = 1
        attempts_file.write_text(str(attempts))
        if attempts < 3:
            print(json.dumps(report, indent=1))
            # Programs that ARE answered must not wait on a flaky
            # sibling's retry budget: merge-record them now so their
            # gates stop re-probing; check_stale keeps the queue
            # retrying for whatever is still missing.
            answered = {n: e for n, e in report["programs"].items()
                        if _settled(e)}
            if answered:
                merged = _merge_write(out_path, report, answered)
                if all(_settled(e) for e in merged["programs"].values()) \
                        and set(merged["programs"]) >= set(PROGRAM_VERSIONS):
                    # Prior settled verdicts fill the gap this run's
                    # flakes left: everything is answered after all.
                    print("[aot-probe] all programs settled after merge",
                          file=sys.stderr)
                    return 0
                print(f"[aot-probe] recorded {sorted(answered)}; sibling "
                      f"exception retryable (attempt {attempts}/3)",
                      file=sys.stderr)
            else:
                print(f"[aot-probe] inconclusive (exception, attempt "
                      f"{attempts}/3) — not recording; will retry next "
                      "cycle", file=sys.stderr)
            return 2
        report["inconclusive_after_attempts"] = attempts

    print(json.dumps(report, indent=1))
    _merge_write(out_path, report, report["programs"])
    return 0


def _run_phase(phase: str, env: dict, timeout_s: float) -> int | None:
    """Run one phase in its own session; kill the whole process group on
    timeout (tunneled-backend children otherwise outlive the parent and
    hold the device). Returns the rc, or None on timeout."""
    import signal

    proc = subprocess.Popen(
        [sys.executable, __file__, "--phase", phase], env=env,
        start_new_session=True)
    try:
        return proc.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        return None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--phase", choices=("a", "b", "both"), default="both")
    ap.add_argument("--check-stale", action="store_true",
                    help="exit 0 if the recorded verdict is current and "
                         "complete, 3 if the probe should (re-)run")
    args = ap.parse_args(argv)

    if args.check_stale:
        return check_stale(pathlib.Path(
            os.environ.get("AOT_LOAD_OUT", str(REPO / "AOT_LOAD.json"))))
    if args.phase == "a":
        phase_a()
        return 0
    if args.phase == "b":
        return phase_b()

    out_path = pathlib.Path(
        os.environ.get("AOT_LOAD_OUT", str(REPO / "AOT_LOAD.json")))
    if cache_is_fresh():
        # Phase A is deterministic; while the backend flakes (phase B exit
        # 2) the queue re-invokes us each cycle — don't recompile
        # identical bytes.
        print("[aot-probe] phase A cache fresh; skipping rebuild",
              file=sys.stderr)
        ra = 0
    else:
        env_a = dict(os.environ, JAX_PLATFORMS="cpu",
                     PYTHONPATH=f"{REPO}:{os.environ.get('PYTHONPATH', '')}")
        ra = _run_phase("a", env_a, 600)
    if ra != 0:
        # Phase A is fully local: a failure (or 600s hang) is deterministic,
        # so write the answer file — the queue must not burn every future
        # health window re-running it.
        out_path.write_text(json.dumps(
            {"ok": False, "stage": "phase-a",
             "program_versions": PROGRAM_VERSIONS,
             "error": "local AOT compile/serialize failed "
                      f"(rc={ra}; timeout if None)"}, indent=1))
        print(f"[aot-probe] phase A failed (rc={ra}); recorded", file=sys.stderr)
        return 1
    env_b = dict(os.environ,
                 PYTHONPATH=f"{REPO}:{os.environ.get('PYTHONPATH', '')}")
    rb = _run_phase("b", env_b, 600)
    if rb is None:
        print("[aot-probe] phase B timed out (backend down?) — retryable",
              file=sys.stderr)
        return 2
    return rb


if __name__ == "__main__":
    sys.exit(main())
