"""TPU application + heatmap benchmark runner (single chip).

Runs the reference's application-level benchmarks on real hardware and
appends records to APPS_TPU.jsonl:

* vanilla fused pairs (`bench_erdos_renyi.cpp` analog) for both kernels,
* ALS-CG and GAT apps (`benchmark_dist.cpp:88-100`),
* the R-sweep heatmap (`bench_heatmap.cpp:33-35`) for both kernels.

Timed loops end in host fetches (utils.platform.force_fetch), so the
numbers are honest on the tunneled backend. Each invocation skips configs
already recorded, so the TPU queue can re-run it after tunnel outages.
"""

from __future__ import annotations

import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from distributed_sddmm_tpu.bench.harness import benchmark_algorithm
from distributed_sddmm_tpu.ops import get_kernel
from distributed_sddmm_tpu.utils.coo import HostCOO

OUT = pathlib.Path(__file__).resolve().parents[1] / "APPS_TPU.jsonl"

# (app, algorithm, logM, npr, R, kernel, trials)
PLAN = [
    ("als", "15d_fusion2", 14, 32, 128, "pallas", 2),
    ("gat", "15d_fusion2", 14, 32, 64, "pallas", 2),
    ("als", "15d_fusion2", 14, 32, 128, "xla", 2),
    ("gat", "15d_fusion2", 14, 32, 64, "xla", 2),
    # heatmap R-sweep (subset of bench_heatmap.cpp's {64..448}: compile cost
    # on this backend bounds the grid; every recorded point is real)
    *[("vanilla", "15d_fusion2", 14, 32, R, k, 5)
      for R in (64, 128, 256, 448) for k in ("pallas", "xla")],
]


def done_keys() -> set:
    keys = set()
    if OUT.exists():
        for line in OUT.read_text().splitlines():
            try:
                r = json.loads(line)
                # R from the plan, not the record: GAT's per-layer
                # set_r_value mutates alg.R before the record is written.
                keys.add((r["app"], r["algorithm"], r["extra"]["logM"],
                          r["extra"]["npr"], r["extra"]["R_req"],
                          r["extra"]["kernel_req"]))
            except (json.JSONDecodeError, KeyError):
                continue
    return keys


def main() -> int:
    import os

    xla_only = os.environ.get("APPS_XLA_ONLY", "") not in ("", "0")
    # APPS_SUBSET splits the plan so the queue can land the short
    # application benches (the round-directive evidence) inside a brief
    # tunnel-health window before committing to the longer heatmap sweep:
    # "apps" = ALS/GAT only, "heatmap" = vanilla R-sweep only, "all".
    subset = os.environ.get("APPS_SUBSET", "all")
    if subset not in ("apps", "heatmap", "all"):
        print(f"unknown APPS_SUBSET={subset!r} (want apps|heatmap|all)",
              file=sys.stderr)
        return 2
    done = done_keys()
    mats: dict = {}
    failures = 0
    for app, alg, log_m, npr, R, kern, trials in PLAN:
        if xla_only and kern != "xla":
            continue  # Mosaic compile service down; run the XLA half
        if subset == "apps" and app == "vanilla":
            continue
        if subset == "heatmap" and app != "vanilla":
            continue
        key = (app, alg, log_m, npr, R, kern)
        if key in done:
            print(f"skip (done): {key}", flush=True)
            continue
        if (log_m, npr) not in mats:
            mats[(log_m, npr)] = HostCOO.rmat(log_m=log_m, edge_factor=npr, seed=0)
        S = mats[(log_m, npr)]
        try:
            rec = benchmark_algorithm(
                S, alg, str(OUT), fused=True, R=R, c=1, app=app,
                trials=trials, kernel=get_kernel(kern),
                extra_info={"extra": {"logM": log_m, "npr": npr,
                                      "R_req": R, "kernel_req": kern}},
            )
            print(json.dumps({"app": app, "R": R, "kernel": kern,
                              "GFLOPs": round(rec["overall_throughput"], 2),
                              "elapsed": round(rec["elapsed"], 3)}), flush=True)
        except Exception as e:  # noqa: BLE001 — record and continue the sweep
            failures += 1
            print(f"FAIL {key}: {type(e).__name__}: {e}", flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
