"""TPU application + heatmap benchmark runner (single chip).

Runs the reference's application-level benchmarks on real hardware and
appends records to APPS_TPU.jsonl:

* vanilla fused pairs (`bench_erdos_renyi.cpp` analog) for both kernels,
* ALS-CG and GAT apps (`benchmark_dist.cpp:88-100`),
* the R-sweep heatmap (`bench_heatmap.cpp:33-35`) for both kernels.

Timed loops end in host fetches (utils.platform.force_fetch), so the
numbers are honest on the tunneled backend. Each invocation skips configs
already recorded, so the TPU queue can re-run it after tunnel outages.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from distributed_sddmm_tpu.bench.harness import benchmark_algorithm
from distributed_sddmm_tpu.ops import get_kernel
from distributed_sddmm_tpu.utils.coo import HostCOO

ROOT = pathlib.Path(__file__).resolve().parents[1]
OUT = ROOT / "APPS_TPU.jsonl"

# (app, algorithm, logM, npr, R, kernel, trials)
PLAN = [
    ("als", "15d_fusion2", 14, 32, 128, "pallas", 2),
    ("gat", "15d_fusion2", 14, 32, 64, "pallas", 2),
    ("als", "15d_fusion2", 14, 32, 128, "xla", 2),
    ("gat", "15d_fusion2", 14, 32, 64, "xla", 2),
    # heatmap R-sweep (subset of bench_heatmap.cpp's {64..448}: compile cost
    # on this backend bounds the grid; every recorded point is real)
    *[("vanilla", "15d_fusion2", 14, 32, R, k, 5)
      for R in (64, 128, 256, 448) for k in ("pallas", "xla")],
]


def done_keys() -> set:
    keys = set()
    if OUT.exists():
        for line in OUT.read_text().splitlines():
            try:
                r = json.loads(line)
                # R from the plan, not the record: GAT's per-layer
                # set_r_value mutates alg.R before the record is written.
                keys.add((r["app"], r["algorithm"], r["extra"]["logM"],
                          r["extra"]["npr"], r["extra"]["R_req"],
                          r["extra"]["kernel_req"]))
            except (json.JSONDecodeError, KeyError):
                continue
    return keys


from distributed_sddmm_tpu.bench.aot import APP_PROGRAM_KEYS as APP_AOT_KEYS  # noqa: E402

_MEMO: dict = {}


def _bench_module():
    """bench.py imported once (ROOT is on sys.path); its code hash cached —
    both would otherwise re-run per plan entry inside the health window."""
    if "bench" not in _MEMO:
        import bench

        _MEMO["bench"] = bench
        _MEMO["hash"] = bench._bench_code_hash()
    return _MEMO["bench"], _MEMO["hash"]


def _aot_post_build(app: str, log_m: int, npr: int, R: int):
    """Returns a benchmark_algorithm post_build hook that injects
    offline-AOT-compiled strategy programs, or None when AOT is not
    validated / not applicable (xla kernel and GAT use the jit path).
    Precompiles in a CPU-pinned subprocess with negative caching."""
    import hashlib
    import subprocess

    if app not in APP_AOT_KEYS:
        return None
    bench, code_hash = _bench_module()
    if not bench._aot_validated("pallas_fused"):
        return None

    from distributed_sddmm_tpu.ops.blocked import knob_env_defaults

    h = hashlib.sha256()
    h.update(code_hash.encode())
    h.update(pathlib.Path(__file__).read_bytes())
    h.update((ROOT / "scripts" / "aot_compile_apps.py").read_bytes())
    h.update("_".join(f"{k}={os.environ.get(k, '')}"
                      for k in sorted(knob_env_defaults())).encode())
    d = ROOT / "artifacts" / "aot_bench" / (
        f"apps_{app}_{log_m}_{npr}_{R}_{h.hexdigest()[:10]}")
    if not (d / "meta.json").exists():
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=f"{ROOT}:{os.environ.get('PYTHONPATH', '')}")
        fail = None
        try:
            proc = subprocess.run(
                [sys.executable,
                 str(ROOT / "scripts" / "aot_compile_apps.py"),
                 app, str(log_m), str(npr), str(R), str(d)],
                env=env, capture_output=True, text=True, timeout=420)
            if proc.returncode != 0:
                fail = "\n".join((proc.stderr or "").strip().splitlines()[-5:])
        except subprocess.TimeoutExpired:
            fail = "timeout after 420s"
        if fail is not None:
            print(f"[apps] AOT precompile failed ({app}): {fail}",
                  file=sys.stderr)
            d.mkdir(parents=True, exist_ok=True)
            (d / "meta.json").write_text(json.dumps({"ok": False,
                                                     "error": fail}))
    try:
        if not json.loads((d / "meta.json").read_text()).get("ok"):
            return None
    except (OSError, json.JSONDecodeError):
        return None

    def hook(alg):
        import jax

        from distributed_sddmm_tpu.bench import aot

        if jax.device_count() != 1:
            return
        for op, use_st in APP_AOT_KEYS[app]:
            name = f"{op}_{'b' if use_st else 'a'}"
            try:
                loaded = aot.load_executable(d, name, 0, jax.devices()[0])
            except Exception as e:  # noqa: BLE001 — jit path covers it
                print(f"[apps] AOT load failed for {name} "
                      f"({type(e).__name__}); jit path", file=sys.stderr)
                continue
            alg.inject_program(op, use_st, loaded)

    return hook


def main() -> int:
    xla_only = os.environ.get("APPS_XLA_ONLY", "") not in ("", "0")
    # APPS_SUBSET splits the plan so the queue can land the short
    # application benches (the round-directive evidence) inside a brief
    # tunnel-health window before committing to the longer heatmap sweep:
    # "apps" = ALS/GAT only, "heatmap" = vanilla R-sweep only, "all".
    subset = os.environ.get("APPS_SUBSET", "all")
    if subset not in ("apps", "heatmap", "all"):
        print(f"unknown APPS_SUBSET={subset!r} (want apps|heatmap|all)",
              file=sys.stderr)
        return 2
    done = done_keys()
    mats: dict = {}
    failures = 0
    for app, alg, log_m, npr, R, kern, trials in PLAN:
        if xla_only and kern != "xla":
            continue  # Mosaic compile service down; run the XLA half
        if subset == "apps" and app == "vanilla":
            continue
        if subset == "heatmap" and app != "vanilla":
            continue
        key = (app, alg, log_m, npr, R, kern)
        if key in done:
            print(f"skip (done): {key}", flush=True)
            continue
        if (log_m, npr) not in mats:
            mats[(log_m, npr)] = HostCOO.rmat(log_m=log_m, edge_factor=npr, seed=0)
        S = mats[(log_m, npr)]
        try:
            hook = None
            if kern == "pallas":
                try:
                    hook = _aot_post_build(app, log_m, npr, R)
                except Exception as e:  # noqa: BLE001 — advisory only:
                    # a broken AOT path (full disk, import failure) must
                    # degrade to the jit measurement, never abort it.
                    print(f"[apps] AOT setup failed ({type(e).__name__}: "
                          f"{e}); jit path", file=sys.stderr)
            rec = benchmark_algorithm(
                S, alg, str(OUT), fused=True, R=R, c=1, app=app,
                trials=trials, kernel=get_kernel(kern),
                extra_info={"extra": {"logM": log_m, "npr": npr,
                                      "R_req": R, "kernel_req": kern}},
                post_build=hook,
            )
            print(json.dumps({"app": app, "R": R, "kernel": kern,
                              "GFLOPs": round(rec["overall_throughput"], 2),
                              "elapsed": round(rec["elapsed"], 3)}), flush=True)
        except Exception as e:  # noqa: BLE001 — record and continue the sweep
            failures += 1
            print(f"FAIL {key}: {type(e).__name__}: {e}", flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
