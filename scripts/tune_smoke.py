"""Closed-loop tuner smoke: detect → re-measure → shadow → hot-swap.

The PR-12 acceptance demo on the CPU test mesh, end to end (a tier-1
test runs this as a subprocess):

1. **adapt** — a deliberately slow incumbent (generic Pallas encoding
   forced onto a skewed R-mat whose fingerprint selects a banked
   variant; its bad plan seeded into a scratch plan cache) serves an
   open-loop faulted load with the background tuner armed. The tuner
   must detect the gap from the live ``padded_lane_frac`` gauge,
   re-measure candidates off the request path (deterministic counted
   trials — PR 9's own arbitration currency on this container),
   shadow-validate the banked challenger bit-for-bit on mirrored
   requests, and hot-swap it mid-load: replies stay bit-identical
   through the swap, the request path performs ZERO live compiles
   during the serving window, a finite ``time_to_adapt_s`` is
   reported, and the plan cache now holds the banked plan for the next
   replica.
2. **mismatch** — the same shadow protocol with a NaN fault installed
   at the challenger replay site (``output:tunerShadow``): promotion
   must be BLOCKED, the ladder untouched, and a flight record dumped.

Usage::

    python scripts/tune_smoke.py [-o out.json]

Prints one JSON report; exit 0 when every check passes, 2 otherwise
(the 0/2 contract ``tests/test_tune_smoke.py`` pins).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def exit_code(report: dict) -> int:
    """The smoke's exit contract: 0 all checks green, 2 otherwise."""
    return 0 if report.get("ok") else 2


def _build_bad_incumbent():
    """A warm ALS serving stack whose strategy pays the generic
    chunk-rounding tax the banked variants exist to remove."""
    from distributed_sddmm_tpu.models.als import DistributedALS
    from distributed_sddmm_tpu.ops.pallas_kernels import PallasKernel
    from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
    from distributed_sddmm_tpu.serve import ALSFoldInTopK, ServingEngine
    from distributed_sddmm_tpu.utils.coo import HostCOO

    # Skewed (R-mat) with a small nnz/row bucket: the population whose
    # short rows pay one mostly-empty chunk per touched column block
    # under the generic geometry — the fingerprint selects a banked
    # variant here, and the counted win is >10%.
    S = HostCOO.rmat(log_m=10, edge_factor=4, seed=0)
    alg = DenseShift15D(
        S, R=8, c=1, fusion_approach=2,
        kernel=PallasKernel(precision="f32", interpret=True),
    )
    model = DistributedALS(alg, S_host=S)
    model.initialize_embeddings()
    # ingest_rows=False pins the problem fingerprint for the demo — a
    # growing live matrix would re-key the plan cache mid-run.
    workload = ALSFoldInTopK(model, k=5, item_buckets=(8,),
                             ingest_rows=False)
    engine = ServingEngine(workload, max_batch=2, max_depth=32,
                           max_wait_ms=2.0)
    return S, model, workload, engine


def check_adapt(tmp: pathlib.Path) -> dict:
    """The headline: detection, off-path re-measure, shadow, hot-swap
    mid-load, all under an injected fault storm."""
    import numpy as np

    from distributed_sddmm_tpu.autotune.cache import PlanCache
    from distributed_sddmm_tpu.autotune.fingerprint import Problem
    from distributed_sddmm_tpu.resilience import FaultPlan, fault_plan
    from distributed_sddmm_tpu.serve import run_load
    from distributed_sddmm_tpu.tuner import BackgroundTuner, TunerConfig
    from distributed_sddmm_tpu.tuner.loop import factory_name

    S, model, workload, engine = _build_bad_incumbent()
    cache = PlanCache(tmp / "plan_cache")
    tuner = BackgroundTuner(
        engine,
        config=TunerConfig(
            interval_s=0.1, lane_frac=0.25, shadow_samples=2,
            cooldown_s=60.0, trial="counted",
        ),
        plan_cache=cache,
    )
    # Seed the deliberately bad plan under the problem's REAL
    # fingerprint key (the one get_plan and the tuner's retune both
    # compute): the generic encoding, stored as if a previous
    # (mis)selection had committed to it — the entry the promotion
    # must overturn in place.
    from distributed_sddmm_tpu.autotune.fingerprint import (
        machine_signature, make_fingerprint,
    )

    incumbent = tuner.incumbent_plan()
    problem = Problem.from_coo(S, model.d_ops.R)
    p, backend, kernels = machine_signature()
    fp_key = make_fingerprint(problem, p, backend, kernels).key
    bad = incumbent.to_dict()
    bad["fingerprint_key"] = fp_key
    cache.store(fp_key, bad)
    assert cache.load(fp_key)["variant"] is None  # the bad plan is live

    engine.start()
    stats_warm = engine.stats()
    rng = np.random.default_rng(7)
    probes = [workload.sample_payload(rng) for _ in range(6)]
    before = [engine.execute_now([p])[0] for p in probes]

    plan = FaultPlan.from_spec("delay")
    tuner.start()
    try:
        with fault_plan(plan):
            summary = run_load(
                engine, duration_s=6.0, rate_hz=30, seed=3, oracle_every=4,
            )
            # Keep draining until the promotion lands or patience runs
            # out (the load window above usually suffices).
            t0 = time.perf_counter()
            while not tuner.promotions and time.perf_counter() - t0 < 20.0:
                for p in probes:
                    try:
                        engine.submit(p)
                    except Exception:  # noqa: BLE001 — shed is fine
                        pass
                time.sleep(0.3)
    finally:
        tuner.stop()
        engine.stop()

    after = [engine.execute_now([p])[0] for p in probes]
    bit_identical = all(
        np.array_equal(a["items"], b["items"])
        and np.array_equal(a["scores"], b["scores"])
        for a, b in zip(before, after)
    )
    stats_end = engine.stats()
    promoted = len(tuner.promotions)
    tta = tuner.time_to_adapt_s
    # The promotion must land on the SAME fingerprint key the bad plan
    # was seeded under — overturning the entry, not writing a sibling.
    overturned = (
        promoted
        and tuner.promotions[0]["plan"]["fingerprint_key"] == fp_key
    )
    cached = cache.load(fp_key) if promoted else None
    swapped_variant = workload.kernel_variant
    return {
        "name": "adapt",
        "ok": bool(
            promoted >= 1
            and overturned
            and swapped_variant is not None
            and tta is not None and tta > 0.0
            and bit_identical
            and stats_end["live_compiles"] == stats_warm["live_compiles"]
            and stats_end["ladder_swaps"] >= 1
            and summary["oracle_failures"] == 0
            and cached is not None
            and cached.get("variant") == swapped_variant
            and cached.get("algorithm") == factory_name(model.d_ops)
        ),
        "promotions": promoted,
        "plan_overturned": bool(overturned),
        "variant": swapped_variant,
        "time_to_adapt_s": tta,
        "bit_identical_across_swap": bit_identical,
        "request_path_compiles": (
            stats_end["live_compiles"] - stats_warm["live_compiles"]
        ),
        "ladder_swaps": stats_end["ladder_swaps"],
        "completed": summary["completed"],
        "oracle_failures": summary["oracle_failures"],
        "faults_fired": len(plan.events),
        "plan_cached": cached is not None,
    }


def check_mismatch(tmp: pathlib.Path) -> dict:
    """Shadow-mismatch safety: a corrupted challenger replay must block
    promotion and dump a flight record; the serving ladder stays on the
    incumbent."""
    import numpy as np

    from distributed_sddmm_tpu.obs import flightrec
    from distributed_sddmm_tpu.resilience import FaultPlan, fault_plan
    from distributed_sddmm_tpu.tuner import ShadowSession
    from distributed_sddmm_tpu.tuner.signals import engine_problem

    S, model, workload, engine = _build_bad_incumbent()
    from distributed_sddmm_tpu.codegen import variant_ids_for

    vid = variant_ids_for(engine_problem(engine))[0]
    engine.warmup()
    fr = flightrec.enable(tmp / "flightrec")
    try:
        shadow = ShadowSession(engine, vid)
        shadow.warm()
        engine.attach_mirror(shadow.offer)
        rng = np.random.default_rng(11)
        payloads = [workload.sample_payload(rng) for _ in range(4)]
        replies = engine.execute_now(payloads[:2])
        shadow.offer(payloads[:2], replies, 2, 8)
        plan = FaultPlan.from_spec(
            '[{"site": "output:tunerShadow", "kind": "nan", "prob": 1.0}]'
        )
        with fault_plan(plan):
            shadow.drain()
        blocked = shadow.mismatches >= 1 and not shadow.clean(1)
        dumped = len(fr.paths) >= 1
    finally:
        engine.detach_mirror()
        flightrec.disable()
    return {
        "name": "mismatch",
        "ok": bool(
            blocked and dumped and engine.stats()["ladder_swaps"] == 0
            and workload.kernel_variant is None
        ),
        "mismatches": shadow.mismatches,
        "flight_records": len(fr.paths),
        "ladder_swaps": engine.stats()["ladder_swaps"],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-o", "--output-file", default=None)
    args = ap.parse_args(argv)

    from distributed_sddmm_tpu.utils.platform import force_cpu_platform

    force_cpu_platform(n_devices=8, replace=True)

    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory() as tmpdir:
        tmp = pathlib.Path(tmpdir)
        checks = [check_adapt(tmp), check_mismatch(tmp)]

    report = {
        "ok": all(c["ok"] for c in checks),
        "elapsed_s": round(time.perf_counter() - t0, 2),
        "checks": checks,
    }
    text = json.dumps(report, indent=1)
    print(text)
    if args.output_file:
        pathlib.Path(args.output_file).write_text(text)
    return exit_code(report)


if __name__ == "__main__":
    sys.exit(main())
