"""CPU-mesh fault-injection smoke: the resilience layer end to end.

Four checks on the same virtual 8-device CPU mesh the test suite uses,
each a compressed version of one fault-matrix row (fast enough for CI; a
tier-1 test runs this as a subprocess):

1. **transient heal** — one injected timeout + one injected NaN on the
   fused op's first dispatches; the retried result must be bit-identical
   to a clean run.
2. **persistent degrade** — every dispatch times out; the op must raise a
   typed error within bounded wall-clock (never hang).
3. **cache garble** — a torn plan-cache write reads back as a miss and
   the next store recovers the key.
4. **kill/resume** — a fault plan crashes ALS between alternating steps;
   resuming from the last checkpoint converges to factors bit-identical
   to an uninterrupted run.

Usage::

    python scripts/resilience_smoke.py [--devices 8] [-o out.json]

Prints one JSON summary; exits nonzero if any check fails.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def check_transient_heal() -> dict:
    import numpy as np

    from distributed_sddmm_tpu.common import MatMode
    from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
    from distributed_sddmm_tpu.resilience import FaultPlan, FaultSpec, fault_plan
    from distributed_sddmm_tpu.utils.coo import HostCOO

    S = HostCOO.erdos_renyi(48, 32, 5, seed=0)

    def fused_fp(alg):
        A = alg.dummy_initialize(MatMode.A)
        B = alg.dummy_initialize(MatMode.B)
        out, mid = alg.fused_spmm(A, B, alg.like_s_values(1.0), MatMode.A)
        return alg.fingerprint(out), alg.fingerprint(mid)

    want = fused_fp(DenseShift15D(S, R=8, c=2, fusion_approach=2))
    # Two sequential dispatches, one fault each: call 1 heals an injected
    # timeout (execute hook, attempt 0), call 2 heals injected NaNs (output
    # hook fires on its first attempt, the guard trips, the retry is clean).
    plan = FaultPlan([
        FaultSpec(site="execute:*", kind="timeout", at=(0,)),
        FaultSpec(site="output:*", kind="nan", at=(1,), param=0.2),
    ])
    with fault_plan(plan):
        alg = DenseShift15D(S, R=8, c=2, fusion_approach=2)
        got1 = fused_fp(alg)
        got2 = fused_fp(alg)
    kinds = {k for _, k, _ in plan.events}
    return {
        "name": "transient_heal",
        "ok": bool(got1 == want and got2 == want
                   and kinds == {"timeout", "nan"}),
        "fired": len(plan.events),
    }


def check_persistent_degrade() -> dict:
    from distributed_sddmm_tpu.common import MatMode
    from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
    from distributed_sddmm_tpu.resilience import FaultPlan, FaultSpec, fault_plan
    from distributed_sddmm_tpu.utils.coo import HostCOO

    S = HostCOO.erdos_renyi(48, 32, 5, seed=0)
    plan = FaultPlan([FaultSpec(site="execute:*", kind="timeout", prob=1.0)])
    t0 = time.monotonic()
    raised = None
    with fault_plan(plan):
        alg = DenseShift15D(S, R=8, c=2, fusion_approach=2)
        try:
            A = alg.dummy_initialize(MatMode.A)
            B = alg.dummy_initialize(MatMode.B)
            alg.fused_spmm(A, B, alg.like_s_values(1.0), MatMode.A)
        except TimeoutError as e:
            raised = f"{type(e).__name__}: {e}"
    elapsed = time.monotonic() - t0
    return {
        "name": "persistent_degrade",
        "ok": bool(raised is not None and elapsed < 60.0),
        "raised": raised,
        "elapsed_s": round(elapsed, 2),
    }


def check_cache_garble(tmp: str) -> dict:
    from distributed_sddmm_tpu.autotune.cache import PlanCache
    from distributed_sddmm_tpu.resilience import FaultPlan, FaultSpec, fault_plan

    cache = PlanCache(pathlib.Path(tmp) / "plan_cache")
    plan_dict = {"algorithm": "15d_fusion2", "c": 2, "kernel": "xla"}
    with fault_plan(FaultPlan(
        [FaultSpec(site="write:smoke.json", kind="truncate", at=(0,), param=0.4)]
    )):
        cache.store("smoke", plan_dict)
    miss_on_garble = cache.load("smoke") is None
    cache.store("smoke", plan_dict)
    recovered = cache.load("smoke") is not None
    return {
        "name": "cache_garble",
        "ok": bool(miss_on_garble and recovered),
        "miss_on_garble": miss_on_garble,
        "recovered": recovered,
    }


def check_kill_resume(tmp: str) -> dict:
    import numpy as np

    from distributed_sddmm_tpu.models.als import DistributedALS
    from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
    from distributed_sddmm_tpu.resilience import (
        CheckpointStore, FaultPlan, FaultSpec, InjectedFault, fault_plan,
    )
    from distributed_sddmm_tpu.utils.coo import HostCOO

    S = HostCOO.erdos_renyi(48, 32, 5, seed=0)

    def make():
        return DistributedALS(
            DenseShift15D(S, R=8, c=2, fusion_approach=2), seed=0, S_host=S
        )

    als = make()
    als.run_cg(4, cg_iters=5)
    want_A, want_B = np.asarray(als.A), np.asarray(als.B)

    store = CheckpointStore(pathlib.Path(tmp) / "ckpt")
    crashed = make()
    crash_seen = False
    with fault_plan(FaultPlan(
        [FaultSpec(site="als:step", kind="error", at=(2,))]
    )):
        try:
            crashed.run_cg(4, cg_iters=5, checkpoint=store, checkpoint_every=1)
        except InjectedFault:
            crash_seen = True

    resumed = make()
    resumed.run_cg(4, cg_iters=5, checkpoint=store, checkpoint_every=1,
                   resume=True)
    identical = bool(
        np.array_equal(np.asarray(resumed.A), want_A)
        and np.array_equal(np.asarray(resumed.B), want_B)
    )
    return {
        "name": "kill_resume",
        "ok": bool(crash_seen and identical),
        "crash_seen": crash_seen,
        "bit_identical": identical,
        "residual": resumed.compute_residual(),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("-o", "--output-file", default=None)
    args = ap.parse_args(argv)

    from distributed_sddmm_tpu.utils.platform import force_cpu_platform

    force_cpu_platform(n_devices=args.devices, replace=True)

    checks = []
    with tempfile.TemporaryDirectory() as tmp:
        for fn in (
            check_transient_heal,
            check_persistent_degrade,
            lambda: check_cache_garble(tmp),
            lambda: check_kill_resume(tmp),
        ):
            try:
                checks.append(fn())
            except Exception as e:  # noqa: BLE001 — a smoke run reports, not raises
                checks.append({
                    "name": getattr(fn, "__name__", "check"),
                    "ok": False,
                    "error": f"{type(e).__name__}: {e}",
                })

    ok = all(c["ok"] for c in checks)
    out = {"ok": ok, "devices": args.devices, "checks": checks}
    blob = json.dumps(out, indent=1)
    print(blob)
    if args.output_file:
        with open(args.output_file, "w") as f:
            f.write(blob + "\n")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
