#!/usr/bin/env bash
# Replication-factor / scaling sweep on a TPU pod — the analog of the
# reference's Cori SLURM sweeps (`/root/reference/jobscript.sh:21-63`,
# c in {1,4,16,64} at fixed problem size).
#
# Usage: TPU_NAME=my-pod ./scripts/pod_sweep.sh [logM] [nnz_per_row] [R]
set -euo pipefail

TPU_NAME=${TPU_NAME:?set TPU_NAME to the tpu-vm name}
LOG_M=${1:-20}
NNZ_PER_ROW=${2:-32}
R=${3:-128}
OUT=${OUT:-sweep_$(date +%Y%m%d_%H%M%S).jsonl}

for C in 1 4 16 64; do
  for ALG in 15d_fusion1 15d_fusion2 15d_sparse 25d_dense_replicate 25d_sparse_replicate; do
    echo "=== c=$C alg=$ALG ==="
    gcloud compute tpus tpu-vm ssh "$TPU_NAME" --worker=all --command \
      "cd ~/distributed_sddmm_tpu && python scripts/run_pod.py \
         er $LOG_M $NNZ_PER_ROW $ALG $R $C --fused both -o $OUT" \
      || echo "skipped (divisibility or OOM)"
  done
done
echo "results in $OUT on each worker; fetch worker 0's copy for charts:"
echo "  python -m distributed_sddmm_tpu.tools.charts $OUT -o charts/"
