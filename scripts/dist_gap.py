"""Measure the tile-kernel vs distributed-program gap on one chip.

The headline bench times the FULL 1.5D dense-shift fused program (shard_map
ring + relayouts + the Pallas tile kernel); scripts/tune_blocks.py times the
bare tile kernel. Round 2 recorded 47 GFLOP/s for the former when the latter
measured 73 — this script pins down how much of that gap remains by timing
both in ONE process on the same matrix at the tuned kernel config, plus the
transpose/pad relayouts (`PallasKernel.prep`) alone.

Appends one JSON record to DIST_GAP.jsonl. Resumable: skips when a record
for the current (logM, npr, R, blocks, group, scatter, chunk, batch,
backend) configuration exists.

Usage: python scripts/dist_gap.py [logM npr R trials]
"""

from __future__ import annotations

import json
import os
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

import numpy as np

OUT = REPO / "DIST_GAP.jsonl"


def _apply_tuned_env(log_m: int, npr: int, R: int) -> None:
    """Measure the SAME kernel config the headline bench would run: apply
    bench.py's best-measured env overrides (explicit env still wins). Must
    run before the package import — the knobs snapshot at import time."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    os.environ.setdefault("BENCH_LOG_M", str(log_m))
    os.environ.setdefault("BENCH_NNZ_PER_ROW", str(npr))
    os.environ.setdefault("BENCH_R", str(R))
    tuned = bench._best_measured_env() or {}
    for k, v in tuned.items():
        os.environ.setdefault(k, v)


def main() -> int:
    log_m = int(sys.argv[1]) if len(sys.argv) > 1 else 16
    npr = int(sys.argv[2]) if len(sys.argv) > 2 else 32
    R = int(sys.argv[3]) if len(sys.argv) > 3 else 128
    trials = int(sys.argv[4]) if len(sys.argv) > 4 else 5
    _apply_tuned_env(log_m, npr, R)

    import jax
    import jax.numpy as jnp

    from distributed_sddmm_tpu.bench.kernels import _chain_time
    from distributed_sddmm_tpu.common import MatMode
    from distributed_sddmm_tpu.ops.blocked import (
        CHUNK, DEFAULT_BLOCK_COLS, DEFAULT_BLOCK_ROWS, DEFAULT_GROUP,
        build_blocked,
    )
    from distributed_sddmm_tpu.ops.pallas_kernels import BlockedTile, PallasKernel
    from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
    from distributed_sddmm_tpu.utils.coo import HostCOO

    kern = PallasKernel()
    cfg = {
        "logM": log_m, "npr": npr, "R": R,
        "blocks": f"{DEFAULT_BLOCK_ROWS}x{DEFAULT_BLOCK_COLS}",
        "group": DEFAULT_GROUP, "scatter_form": kern.scatter_form,
        "chunk": CHUNK, "batch_step": kern.batch_step,
        "backend": jax.default_backend(),
    }
    if OUT.exists():
        for line in OUT.read_text().splitlines():
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if all(rec.get(k) == v for k, v in cfg.items()):
                print(f"skip (done): {cfg}", flush=True)
                return 0

    S = HostCOO.rmat(log_m=log_m, edge_factor=npr, seed=0)
    flops_pair = 2.0 * S.nnz * 2.0 * R
    rng = np.random.default_rng(0)

    # --- bare tile kernel (tune_blocks.py's measurement) ----------------- #
    meta = build_blocked(
        1, np.zeros(S.nnz, np.int64), S.rows, S.cols, S.M, S.N,
        block_rows=DEFAULT_BLOCK_ROWS, block_cols=DEFAULT_BLOCK_COLS,
        group=DEFAULT_GROUP,
    )
    blk = BlockedTile(
        lr=jnp.array(meta.lr[0]), lc=jnp.array(meta.lc[0]),
        meta=jnp.array(meta.meta[0]), bm=meta.bm, bn=meta.bn,
        gr_blocks=meta.gr_blocks, gc_blocks=meta.gc_blocks, group=meta.group,
    )
    vals_np = np.zeros(meta.n_chunks * CHUNK, np.float32)
    vals_np[meta.host_to_chunk] = 1.0
    cvals = jnp.array(vals_np)
    A = jnp.array(rng.standard_normal((S.M, R)), jnp.float32)
    B = jnp.array(rng.standard_normal((S.N, R)), jnp.float32)

    def tile_step(state):
        Bs, _ = state
        o, _mid = kern.fused_tile(blk, cvals, A, Bs)
        return (Bs + o[: S.N] * 1e-12, _)

    t_tile = _chain_time(tile_step, (B, cvals), trials)

    # --- relayouts alone (prep A + prep B) ------------------------------- #
    # Both operands ride the loop carry: a closure-constant prep would be
    # hoisted out of the timed fori_loop by XLA's invariant code motion.
    def prep_step(state):
        As, Bs = state
        at = kern.prep(As, meta.rows_pad)
        bt = kern.prep(Bs, meta.cols_pad)
        s = at.astype(jnp.float32).sum() + bt.astype(jnp.float32).sum()
        return (As + s * 1e-30, Bs + s * 1e-30)

    t_prep = _chain_time(prep_step, (A, B), trials)

    # --- full distributed fused program (bench.py's measurement) --------- #
    alg = DenseShift15D(S, R=R, c=1, fusion_approach=2, kernel=kern)
    Ad = alg.dummy_initialize(MatMode.A)
    Bd = alg.like_b_matrix(0.01)
    pair = alg.fused_program(alg.like_s_values(1.0), MatMode.A)

    def dist_step(state):
        Ab, _ = state
        out, _mid = pair(Ab, Bd)
        return (Ab + out * 1e-12, _)

    t_dist = _chain_time(dist_step, (Ad, cvals), trials)

    rec = dict(cfg)
    rec.update(
        tile_ms=t_tile * 1e3, dist_ms=t_dist * 1e3, prep_ms=t_prep * 1e3,
        tile_gflops=flops_pair / t_tile / 1e9,
        dist_gflops=flops_pair / t_dist / 1e9,
        dist_over_tile=t_dist / t_tile,
    )
    with OUT.open("a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    main()
