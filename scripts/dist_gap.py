"""Measure the tile-kernel vs distributed-program gap on one chip.

The headline bench times the FULL 1.5D dense-shift fused program (shard_map
ring + relayouts + the Pallas tile kernel); scripts/tune_blocks.py times the
bare tile kernel. Round 2 recorded 47 GFLOP/s for the former when the latter
measured 73 — this script pins down how much of that gap remains by timing
both in ONE process on the same matrix at the tuned kernel config, plus the
transpose/pad relayouts (`PallasKernel.prep`) alone.

AOT mode: when AOT_LOAD.json validates re-homed loads, the three distinct
programs (tile, prep, dist) are loaded from offline-compiled executables
instead of paying three on-device Mosaic compiles. The dist program is
byte-identical to bench.py's headline chain, so it reuses bench's AOT
cache; tile/prep get their own (`--aot-compile` builds them, CPU-pinned).
Any AOT failure falls back to on-device jit per program.

Appends one JSON record to DIST_GAP.jsonl. Resumable: skips when a record
for the current (logM, npr, R, blocks, group, scatter, chunk, batch,
backend) configuration exists.

Usage: python scripts/dist_gap.py [logM npr R trials]
       python scripts/dist_gap.py --aot-compile OUT_DIR [logM npr R trials]
"""

from __future__ import annotations

import importlib.util
import json
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

import numpy as np

OUT = REPO / "DIST_GAP.jsonl"


def _load_bench():
    spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


def _apply_tuned_env(bench, log_m: int, npr: int, R: int) -> None:
    """Measure the SAME kernel config the headline bench would run: apply
    bench.py's best-measured env overrides (explicit env still wins). Must
    run before the package import — the knobs snapshot at import time."""
    os.environ.setdefault("BENCH_LOG_M", str(log_m))
    os.environ.setdefault("BENCH_NNZ_PER_ROW", str(npr))
    os.environ.setdefault("BENCH_R", str(R))
    tuned = bench._best_measured_env() or {}
    for k, v in tuned.items():
        os.environ.setdefault(k, v)


def build_tile_setup(kern, log_m: int, npr: int, R: int):
    """The bare-tile and relayout step functions + states (shared between
    the measuring process and the offline AOT compiler)."""
    import jax.numpy as jnp

    from distributed_sddmm_tpu.ops.blocked import (
        CHUNK, DEFAULT_BLOCK_COLS, DEFAULT_BLOCK_ROWS, DEFAULT_GROUP,
        build_blocked,
    )
    from distributed_sddmm_tpu.ops.pallas_kernels import BlockedTile
    from distributed_sddmm_tpu.utils.coo import HostCOO

    S = HostCOO.rmat(log_m=log_m, edge_factor=npr, seed=0)
    rng = np.random.default_rng(0)
    meta = build_blocked(
        1, np.zeros(S.nnz, np.int64), S.rows, S.cols, S.M, S.N,
        block_rows=DEFAULT_BLOCK_ROWS, block_cols=DEFAULT_BLOCK_COLS,
        group=DEFAULT_GROUP,
    )
    blk = BlockedTile(
        lr=jnp.array(meta.lr[0]), lc=jnp.array(meta.lc[0]),
        meta=jnp.array(meta.meta[0]), bm=meta.bm, bn=meta.bn,
        gr_blocks=meta.gr_blocks, gc_blocks=meta.gc_blocks, group=meta.group,
    )
    vals_np = np.zeros(meta.n_chunks * CHUNK, np.float32)
    vals_np[meta.host_to_chunk] = 1.0
    cvals = jnp.array(vals_np)
    A = jnp.array(rng.standard_normal((S.M, R)), jnp.float32)
    B = jnp.array(rng.standard_normal((S.N, R)), jnp.float32)

    def tile_step(state):
        Bs, _ = state
        o, _mid = kern.fused_tile(blk, cvals, A, Bs)
        return (Bs + o[: S.N] * 1e-12, _)

    def prep_step(state):
        # Both operands ride the loop carry: a closure-constant prep would
        # be hoisted out of the timed fori_loop by invariant code motion.
        As, Bs = state
        at = kern.prep(As, meta.rows_pad)
        bt = kern.prep(Bs, meta.cols_pad)
        s = at.astype(jnp.float32).sum() + bt.astype(jnp.float32).sum()
        return (As + s * 1e-30, Bs + s * 1e-30)

    steps = {"tile": (tile_step, (B, cvals)), "prep": (prep_step, (A, B))}
    return S, meta, steps


def _tile_cache_dir(bench, log_m: int, npr: int, R: int, trials: int) -> pathlib.Path:
    """Cache key: grid + trials + every kernel knob's RESOLVED value (the
    tuned env changes without source changes — a bt-compiled executable
    must not be timed under a cfg that says nt) + bench's all-sources hash
    + this file (the step functions live here)."""
    import hashlib

    from distributed_sddmm_tpu.ops.blocked import knob_env_defaults

    h = hashlib.sha256()
    h.update(bench._bench_code_hash().encode())
    h.update(pathlib.Path(__file__).read_bytes())
    knobs = "_".join(f"{k}={os.environ.get(k, '')}"
                     for k in sorted(knob_env_defaults()))
    h.update(knobs.encode())
    return REPO / "artifacts" / "aot_bench" / (
        f"distgap_{log_m}_{npr}_{R}_t{trials}_{h.hexdigest()[:10]}")


def aot_compile(out_dir: pathlib.Path, log_m: int, npr: int, R: int,
                trials: int) -> int:
    """Offline (CPU-pinned): compile + serialize the tile/prep chains."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from jax.experimental import topologies

    from distributed_sddmm_tpu.bench import aot
    from distributed_sddmm_tpu.ops.pallas_kernels import PallasKernel

    kern = PallasKernel(precision="bf16", interpret=False)
    _S, _meta, steps = build_tile_setup(kern, log_m, npr, R)
    topo = topologies.get_topology_desc(platform="tpu", topology_name="v5e:2x4")
    report = {"ok": True, "compile_s": {}}
    for name, (step, state) in steps.items():
        report["compile_s"][name] = aot.compile_chain_pair(
            step, state, trials, topo.devices[0], out_dir, name)
    (out_dir / "meta.json").write_text(json.dumps(report, indent=1))
    print(json.dumps(report))
    return 0


def _timed(name: str, step, state, trials: int, load_dir) -> tuple[float, bool]:
    """AOT-loaded timing when available, on-device `_chain_time` otherwise;
    returns (seconds, used_aot)."""
    import jax

    from distributed_sddmm_tpu.bench.kernels import _chain_time

    if load_dir is not None:
        from distributed_sddmm_tpu.bench import aot

        try:
            loaded = aot.load_chain_pair(load_dir, name, trials,
                                         jax.devices()[0])
            return aot.chain_time_loaded(loaded, state, trials), True
        except Exception as e:  # noqa: BLE001 — any AOT failure -> jit path
            print(f"[dist-gap] AOT path failed for {name} "
                  f"({type(e).__name__}: {e}); on-device compile",
                  file=sys.stderr)
    return _chain_time(step, state, trials), False


def main() -> int:
    argv = [a for a in sys.argv[1:]]
    compile_dir = None
    if argv and argv[0] == "--aot-compile":
        compile_dir = pathlib.Path(argv[1])
        argv = argv[2:]
    log_m = int(argv[0]) if len(argv) > 0 else 16
    npr = int(argv[1]) if len(argv) > 1 else 32
    R = int(argv[2]) if len(argv) > 2 else 128
    trials = int(argv[3]) if len(argv) > 3 else 5

    bench = _load_bench()
    _apply_tuned_env(bench, log_m, npr, R)
    # bench's AOT cache + compiler read the trip count from the env; a
    # mismatch would serialize pairs the loader can never find. When
    # BENCH_TRIALS was already exported it wins over argv, so re-derive
    # trials from the env — loader and compiler must agree on the names.
    os.environ.setdefault("BENCH_TRIALS", str(trials))
    try:
        trials = int(os.environ["BENCH_TRIALS"])
    except ValueError:
        # Malformed export: fall back to argv and force agreement.
        os.environ["BENCH_TRIALS"] = str(trials)

    if compile_dir is not None:
        compile_dir.mkdir(parents=True, exist_ok=True)
        return aot_compile(compile_dir, log_m, npr, R, trials)

    import jax
    import jax.numpy as jnp

    from distributed_sddmm_tpu.ops.pallas_kernels import PallasKernel
    from distributed_sddmm_tpu.ops.blocked import (
        CHUNK, DEFAULT_BLOCK_COLS, DEFAULT_BLOCK_ROWS, DEFAULT_GROUP,
    )

    kern = PallasKernel()
    cfg = {
        "logM": log_m, "npr": npr, "R": R,
        "blocks": f"{DEFAULT_BLOCK_ROWS}x{DEFAULT_BLOCK_COLS}",
        "group": DEFAULT_GROUP, "scatter_form": kern.scatter_form,
        "chunk": CHUNK, "batch_step": kern.batch_step,
        "backend": jax.default_backend(),
    }
    if OUT.exists():
        for line in OUT.read_text().splitlines():
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if all(rec.get(k) == v for k, v in cfg.items()):
                print(f"skip (done): {cfg}", flush=True)
                return 0

    S, meta, steps = build_tile_setup(kern, log_m, npr, R)
    flops_pair = 2.0 * S.nnz * 2.0 * R

    # Offline-compile the tile/prep chains when loads are validated (the
    # subprocess is local + seconds; failures fall back per program).
    tile_dir = None
    if jax.device_count() == 1 and bench._aot_validated("pallas_fused"):
        d = _tile_cache_dir(bench, log_m, npr, R, trials)
        if not (d / "meta.json").exists():
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            fail = None
            try:
                proc = subprocess.run(
                    [sys.executable, __file__, "--aot-compile", str(d),
                     str(log_m), str(npr), str(R), str(trials)],
                    env=env, capture_output=True, text=True, timeout=420)
                if proc.returncode > 0:
                    fail = "\n".join(
                        (proc.stderr or "").strip().splitlines()[-5:])
                elif proc.returncode < 0:
                    # Signal kill (OOM etc.) — transient, no tombstone.
                    print(f"[dist-gap] AOT precompile killed "
                          f"(rc={proc.returncode}); on-device compile "
                          "this run", file=sys.stderr)
            except subprocess.TimeoutExpired:
                # Same strike policy as bench/kernel_sweep (aot_gate):
                # skip AOT this run; tombstone only after timeouts from
                # two independent load episodes.
                print("[dist-gap] AOT precompile timed out; on-device "
                      "compile this run", file=sys.stderr)
                if bench._aot_gate().timeout_strike(d):
                    fail = "repeated timeouts (420s budget)"
            if fail is not None and not (d / "meta.json").exists():
                # Negative cache + diagnostics: a deterministic local
                # compile failure must not re-spend its timeout each run.
                # An existing meta is the compiler's own verdict (written
                # as its final act) — never clobber it with ours.
                print(f"[dist-gap] AOT precompile failed: {fail}",
                      file=sys.stderr)
                d.mkdir(parents=True, exist_ok=True)
                (d / "meta.json").write_text(
                    json.dumps({"ok": False, "error": fail}))
        try:
            if json.loads((d / "meta.json").read_text()).get("ok"):
                tile_dir = d
        except (OSError, json.JSONDecodeError):
            tile_dir = None

    tile_step, tile_state = steps["tile"]
    prep_step, prep_state = steps["prep"]
    t_tile, aot_tile = _timed("tile", tile_step, tile_state, trials, tile_dir)
    t_prep, aot_prep = _timed("prep", prep_step, prep_state, trials, tile_dir)

    # --- full distributed fused program (bench.py's measurement) --------- #
    # Identical to the headline chain; reuse bench's builder + AOT cache.
    alg, prog, Ad, Bd, targs = bench.build_headline(kern)
    dist_dir = bench._maybe_aot_dir({}) if jax.device_count() == 1 else None
    t_dist = None
    aot_dist = False
    if dist_dir:
        from distributed_sddmm_tpu.bench import aot

        try:
            chains = aot.load_chain_pair(dist_dir, "headline", trials,
                                         jax.devices()[0])

            def run(n):
                return float(chains[n](Ad, Bd, *targs).sum())

            t_dist = aot.timed_difference(run, trials)
            aot_dist = True
        except Exception as e:  # noqa: BLE001 — fall back to on-device jit
            print(f"[dist-gap] AOT dist path failed ({type(e).__name__}: "
                  f"{e}); on-device compile", file=sys.stderr)
            t_dist = None
    if t_dist is None:
        from distributed_sddmm_tpu.bench.kernels import _chain_time

        def dist_step(state):
            Ab, _ = state
            out, _mid = prog(Ab, Bd, *targs)
            return (Ab + out * 1e-12, _)

        t_dist = _chain_time(dist_step, (Ad, jnp.zeros(())), trials)

    rec = dict(cfg)
    rec.update(
        tile_ms=t_tile * 1e3, dist_ms=t_dist * 1e3, prep_ms=t_prep * 1e3,
        tile_gflops=flops_pair / t_tile / 1e9,
        dist_gflops=flops_pair / t_dist / 1e9,
        dist_over_tile=t_dist / t_tile,
        aot={"tile": aot_tile, "prep": aot_prep, "dist": aot_dist},
    )
    with OUT.open("a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
