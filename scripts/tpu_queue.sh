#!/bin/bash
# TPU work queue with tunnel-health gating.
#
# The tunneled TPU backend in this environment goes down for stretches
# (backend init or the remote Mosaic compile service hang). This watchdog
# polls health with a short-timeout probe and, while healthy, drains the
# queued benchmark plans one at a time (never two TPU processes at once).
# Everything is resumable: kernel_sweep.py skips configs already recorded.
#
# Usage: bash scripts/tpu_queue.sh <max_hours>

set -u
cd "$(dirname "$0")/.."
MAX_HOURS=${1:-6}
DEADLINE=$(( $(date +%s) + MAX_HOURS * 3600 ))
export PYTHONPATH="/root/repo:${PYTHONPATH:-}"

healthy() {
  timeout 180 python - <<'EOF' >/dev/null 2>&1
import jax, jax.numpy as jnp
from jax.experimental import pallas as pl
x = jnp.ones((256, 256))
def body(x_ref, o_ref):
    o_ref[:] = x_ref[:] * 2.0
y = pl.pallas_call(body, out_shape=jax.ShapeDtypeStruct((256, 256), jnp.float32))(x)
assert float(y.sum()) == 2 * 256 * 256
EOF
}

run_step() {  # run_step <cmd...> — steps are themselves resumable (they
  # skip configs already recorded), so no done-markers: a completed step
  # re-run costs only its output scan.
  echo "[queue] $(date +%H:%M:%S) running: $*"
  if "$@"; then
    echo "[queue] done: $*"
  else
    echo "[queue] FAILED (rc=$?): $*"
    return 1
  fi
}

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if ! healthy; then
    echo "[queue] $(date +%H:%M:%S) TPU unhealthy; sleeping 600s"
    sleep 600
    continue
  fi
  echo "[queue] $(date +%H:%M:%S) TPU healthy"

  # 1. chunk-group probe (feeds the DEFAULT_GROUP decision)
  run_step python scripts/kernel_sweep.py \
    scripts/plans/group_probe.json KERNELS_TPU.jsonl --timeout 900 --retries 1 \
    || { sleep 300; continue; }

  # 2. star sweep, XLA vs Pallas (KERNELS_TPU artifact)
  run_step python scripts/kernel_sweep.py \
    scripts/plans/star_sweep.json KERNELS_TPU.jsonl --timeout 1500 --retries 1 \
    || { sleep 300; continue; }

  # 3. application + heatmap benches (APPS_TPU artifact; self-resuming)
  run_step timeout 7200 python scripts/tpu_apps.py \
    || { sleep 300; continue; }

  echo "[queue] all steps complete"
  break
done
