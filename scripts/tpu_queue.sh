#!/bin/bash
# TPU work queue with tunnel-health gating.
#
# The tunneled TPU backend in this environment goes down for stretches, in
# two distinct modes: the whole backend (init hangs / UNAVAILABLE) or only
# the remote Mosaic compile service (plain XLA works, Pallas compiles
# hang). This watchdog probes both tiers and drains whatever work the
# current health allows, one TPU process at a time. Every step is
# resumable (kernel_sweep.py / tpu_apps.py skip configs already recorded),
# so completed steps re-run for only an output scan.
#
# Usage: bash scripts/tpu_queue.sh <max_hours>

set -u
cd "$(dirname "$0")/.."
MAX_HOURS=${1:-6}
DEADLINE=$(( $(date +%s) + MAX_HOURS * 3600 ))
export PYTHONPATH="/root/repo:${PYTHONPATH:-}"

# Persistent XLA compilation cache for every child: executables compiled
# BY the axon backend reload on the same build, so a program pays its
# 2-12 min remote Mosaic compile once per session instead of once per
# subprocess (retry cycles, dist_gap's reuse of the headline chain, apps
# re-runs). This is the working replacement for the dead local-AOT path —
# AOT_LOAD.json records that LOCALLY-serialized executables can never
# load here (axon format vX vs build v9), but same-build cache entries
# carry no such mismatch. Gitignored: entries die with the container.
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-/root/repo/artifacts/xla_cache}"
mkdir -p "$JAX_COMPILATION_CACHE_DIR"

# Offline Mosaic compile pre-flight (local CPU + topology AOT, no tunnel):
# refresh PREFLIGHT.json so the sweeps skip configs that cannot compile
# instead of timing out on them inside a scarce health window. Skipped
# when the recorded preflight is newer than everything that could change
# its answer — a queue relaunch must not spend ~6 min of a potential
# health window re-proving an unchanged record.
# "Fresh" = mtime-newer than every input AND complete: preflight flushes
# its report (touching the mtime) after every config, so a timeout-killed
# partial run would otherwise pass the mtime check forever.
if find distributed_sddmm_tpu scripts/preflight_kernels.py scripts/plans \
     -newer PREFLIGHT.json 2>/dev/null | grep -q . || [ ! -f PREFLIGHT.json ] \
   || ! python -c "import json,sys; \
        sys.exit(0 if json.load(open('PREFLIGHT.json')).get('complete') else 1)" \
        2>/dev/null; then
  timeout 900 python scripts/preflight_kernels.py \
    || echo "[queue] preflight had failures (bad configs will be skipped)"
else
  echo "[queue] PREFLIGHT.json fresh and complete; skipping preflight"
fi

healthy_basic() {  # backend up: devices + a matmul round-trip
  timeout 150 python - <<'EOF' >/dev/null 2>&1
import jax, jax.numpy as jnp
x = jnp.ones((256, 256))
assert float((x @ x).sum()) == 256.0 * 256 * 256
EOF
}

healthy_pallas() {  # Mosaic compile service also up
  # Cache OFF for this probe: a persisted executable from an earlier
  # window would "pass" without touching the remote Mosaic service this
  # tier gate exists to probe, routing novel-compile sweeps into a
  # Mosaic outage where each one hangs to its full timeout.
  env -u JAX_COMPILATION_CACHE_DIR timeout 240 python - <<'EOF' >/dev/null 2>&1
import jax, jax.numpy as jnp
from jax.experimental import pallas as pl
def body(x_ref, o_ref):
    o_ref[:] = x_ref[:] * 2.0
y = pl.pallas_call(body, out_shape=jax.ShapeDtypeStruct((256, 256), jnp.float32))(jnp.ones((256, 256)))
assert float(y.sum()) == 2 * 256 * 256
EOF
}

run_step() {
  echo "[queue] $(date +%H:%M:%S) running: $*"
  if "$@"; then
    echo "[queue] done: $*"
  else
    echo "[queue] FAILED (rc=$?): $*"
    return 1
  fi
}

# Measurement records are only durable once committed: the round-5 container
# reset threw away an ANSWERED AOT_LOAD.json (plus session logs) because the
# queue wrote but never committed it. Commit the record files after every
# drained tier; no-op when nothing changed. Only ever `add`s the known
# record paths — never package sources, so a mid-edit working tree can't be
# swept into a queue commit.
commit_records() {
  local msg=${1:-"Queue: bank measurement records"}
  local all=(AOT_LOAD.json KERNELS_TPU.jsonl KERNELS_TPU.md DIST_GAP.jsonl
    APPS_TPU.jsonl PREFLIGHT.json artifacts/bench_midround
    artifacts/tpu_breakdown artifacts/kernels_chart artifacts/costmodel)
  local paths=() p
  for p in "${all[@]}"; do [ -e "$p" ] && paths+=("$p"); done
  [ ${#paths[@]} -eq 0 ] && return 0
  if [ -n "$(git status --porcelain -- "${paths[@]}" 2>/dev/null)" ]; then
    git add -A -- "${paths[@]}" 2>/dev/null
    # Pathspec-limited commit: an interactive session's concurrently
    # staged files must never ride along in a queue commit.
    git commit -q -m "$msg" -- "${paths[@]}" 2>/dev/null \
      && echo "[queue] committed: $msg"
  fi
}

# Mid-round headline banking: the driver runs bench.py at round END, which
# loses the round's headline if the tunnel is down right then. Bank a
# real-TPU full-program record from THIS window; bench.py's fallback path
# reports it (clearly noted) when the end-of-round run can't reach the
# chip. BENCH_SKIP_CPU_FALLBACK because a CPU record can never be banked;
# bench.py --validate-midround is the ONE validator (shared with the
# fallback reader) of what counts as bankable. $1 = outer timeout,
# $2 = "xla" when called from the Mosaic-outage rescue tier.
bank_headline() {
  local t=$1 kern=${2:-}
  local dir=artifacts/bench_midround rec=artifacts/bench_midround/record.json
  mkdir -p "$dir"
  # The knob overrides bench.py will apply from the best measured record
  # (bench._best_measured_env). When new sweep points change this tuning
  # (e.g. the step-batch probe landing 195 GFLOP/s tiles vs the 83.6 the
  # first bank ran under), the banked headline must be re-attempted: the
  # driver-visible number should track the best MEASURED config, not the
  # knobs of whichever window happened to bank first.
  local tune_sig
  tune_sig=$(python - <<'EOF' 2>/dev/null
import importlib.util, json
spec = importlib.util.spec_from_file_location('b', 'bench.py')
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
print(json.dumps(m._best_measured_env(), sort_keys=True))
EOF
  )
  # "Exists" is not "valid": a record whose code_hash no longer matches
  # current sources would be rejected by the fallback reader anyway, so
  # it must not block re-banking — run it through the one validator. The
  # merge below also needs this: a stale record's value must not outbid
  # a fresh valid one.
  local old_valid=0
  if [ -f "$rec" ] && python bench.py --validate-midround "$rec"; then
    old_valid=1
    # The xla rescue tier never touches a valid record.
    if [ -n "$kern" ]; then
      return 0
    fi
    if ! grep -q "xla kernel" "$rec"; then
      # Valid Pallas record: re-attempt ONLY when the measured tuning
      # changed since it was banked (strict > in the merge keeps the
      # better record either way, so a re-attempt can't lose ground).
      if [ "$(cat "$dir/banked_env" 2>/dev/null)" = "$tune_sig" ]; then
        return 0
      fi
      echo "[queue] measured tuning changed since last bank; re-banking"
    else
      # A record banked by the slower xla rescue kernel: upgrade to the
      # Pallas kernel a bounded number of times (each attempt costs up
      # to $t seconds of a scarce window).
      local n=0
      [ -f "$dir/upgrade_attempts" ] && n=$(cat "$dir/upgrade_attempts")
      if [ "$n" -ge 2 ]; then
        echo "[queue] pallas upgrade attempts exhausted; keeping xla record"
        return 0
      fi
      echo $((n + 1)) > "$dir/upgrade_attempts"
    fi
  fi
  local extra=(BENCH_SKIP_CPU_FALLBACK=1)
  [ -n "$kern" ] && extra+=(BENCH_KERNEL="$kern")
  if run_step timeout "$t" env "${extra[@]}" bash -c \
      'python bench.py > artifacts/bench_midround/record.tmp'; then
    if python bench.py --validate-midround \
        artifacts/bench_midround/record.tmp; then
      BANK_OLD_VALID=$old_valid python - <<'EOF'
import json, os
p = "artifacts/bench_midround/"
new = json.loads(open(p + "record.tmp").read().strip().splitlines()[-1])
old = {"value": 0.0}
# An INVALID pre-existing record (stale code_hash) must not outbid a
# fresh valid one — its value only competes when the validator passed.
if os.environ.get("BANK_OLD_VALID") == "1":
    try:
        old = json.loads(
            open(p + "record.json").read().strip().splitlines()[-1])
    except Exception:
        pass
# Strict >: when all live attempts fail, bench.py's fallback prints the
# EXISTING banked record back out (equal value) — replacing with that
# self-referential copy must not be logged as a fresh bank.
if new.get("value", 0.0) > old.get("value", 0.0):
    os.replace(p + "record.tmp", p + "record.json")
    # A newly banked record starts with a fresh pallas-upgrade budget.
    try:
        os.unlink(p + "upgrade_attempts")
    except FileNotFoundError:
        pass
    print(f"[queue] banked mid-round real-TPU headline: {new['value']} "
          f"{new.get('unit', '')}")
else:
    print(f"[queue] kept existing banked record "
          f"({old['value']} >= {new['value']})")
EOF
      # The attempt ran to completion under this tuning — don't re-attempt
      # until the measured tuning changes again. (A failed/timed-out
      # attempt falls through without recording, so it retries next cycle.)
      echo "$tune_sig" > "$dir/banked_env"
    else
      echo "[queue] bench produced no bankable TPU record"
    fi
  fi
  return 0
}

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if ! healthy_basic; then
    echo "[queue] $(date +%H:%M:%S) TPU backend down; sleeping 600s"
    sleep 600
    continue
  fi
  if healthy_pallas; then
    echo "[queue] $(date +%H:%M:%S) TPU fully healthy (pallas ok)"
    # Sweep steps are resumable and retry internally; a PARTIAL failure
    # (rc=1 with some configs done) must not trap the queue re-probing the
    # same pathological config before later steps ever run. Record the
    # failure, finish the rest of the pipeline, then cycle back so only the
    # missing configs re-run. A Mosaic-tier outage mid-pipeline is caught by
    # the re-probe before tpu_apps and routes back to the tier gates.
    failed=""
    # One-shot AOT-load probe first (~2 min): if locally AOT-compiled
    # executables can load on the tunneled chip, every later sweep compile
    # can move off-chip. Self-recording; skipped once answered. Exit 2 =
    # backend flaked mid-probe (no answer written) — retry next cycle. The
    # probe bounds its own phases (600s each, process-group kills); the
    # outer timeout is a generous backstop above that worst case.
    # --check-stale: exit 0 = recorded verdict current+complete; any
    # other rc (3 = missing/stale/incomplete, 1 = checker crashed) =
    # (re-)probe. Verdicts from older per-program chain versions (e.g.
    # v1's bf16-precision xla false-negative) are pruned by the checker
    # while still-valid sibling verdicts keep gating their AOT modes.
    if ! python scripts/aot_load_probe.py --check-stale; then
      run_step timeout 1500 python scripts/aot_load_probe.py || true
      commit_records "Queue: AOT-load probe verdict"
    fi
    # Mid-round headline record: the driver runs bench.py at round END,
    # which loses the round's headline if the tunnel is down right then.
    # Bank a real-TPU full-program record from THIS healthy window; the
    # bench's fallback path reports it (clearly noted) if the end-of-round
    # run can't reach the chip. Kept only when the measuring backend was
    # really the TPU (bench records its backend per attempt). Runs first:
    # it is the driver's primary metric, and its tuned kernel config is
    # long-measured (known-compilable).
    # A record banked by the XLA-only tier (Mosaic-outage rescue kernel)
    # is real but slow; with Mosaic healthy, re-bank for the tuned Pallas
    # kernel and keep whichever record is faster.
    bank_headline 2400
    commit_records "Queue: bank real-TPU headline record"
    # ALS/GAT application records first (round-directive evidence with none
    # yet, and known-compilable kernels): a short health window still
    # records them before the novel kernel-variant probes, whose compiles
    # are the likeliest to hang.
    run_step env APPS_SUBSET=apps timeout 3600 python scripts/tpu_apps.py \
      || failed=1
    commit_records "Queue: ALS/GAT TPU application records"
    # Mosaic may have died mid-apps; re-gate before the probes, whose
    # compiles would each hang to their full timeout.
    if [ -n "$failed" ] && ! healthy_pallas; then continue; fi
    # Cheapest evidence first, in case this window is short: the star
    # sweep's XLA half compiles in ~1-2 min per config (no Mosaic), and
    # dist_gap reuses the headline chain already in the XLA compilation
    # cache from the banking step. Both are round-directive artifacts.
    run_step python scripts/kernel_sweep.py \
      scripts/plans/star_sweep.json KERNELS_TPU.jsonl --timeout 1200 --retries 1 \
      --kernel-filter xla \
      || failed=1
    commit_records "Queue: XLA star-sweep grid points"
    run_step timeout 1800 python scripts/dist_gap.py || true
    commit_records "Queue: tile-vs-distributed gap record"
    # Novel-variant Mosaic probes (5-12 min compile each).
    run_step python scripts/kernel_sweep.py \
      scripts/plans/batch_probe.json KERNELS_TPU.jsonl --timeout 1500 --retries 0 \
      || failed=1
    run_step python scripts/kernel_sweep.py \
      scripts/plans/scatter_probe.json KERNELS_TPU.jsonl --timeout 1500 --retries 0 \
      || failed=1
    run_step python scripts/kernel_sweep.py \
      scripts/plans/chunk_probe.json KERNELS_TPU.jsonl --timeout 1500 --retries 0 \
      || failed=1
    if [ -n "$failed" ] && ! healthy_pallas; then continue; fi
    run_step python scripts/kernel_sweep.py \
      scripts/plans/group_probe.json KERNELS_TPU.jsonl --timeout 1500 --retries 1 \
      || failed=1
    run_step python scripts/kernel_sweep.py \
      scripts/plans/star_sweep.json KERNELS_TPU.jsonl --timeout 1500 --retries 1 \
      || failed=1
    # Full reference cross-product (local_kernel_benchmark.cpp's grid) —
    # affordable ONLY when AOT loads were validated (compiles then cost
    # seconds offline instead of minutes on-chip), so gate on the probe's
    # recorded answer.
    # Gate on the PALLAS probe program specifically: Mosaic on-chip
    # compiles (2-12 min each) are what make the full grid unaffordable;
    # an xla-only validation must not open it (pallas configs would all
    # fall back to on-chip Mosaic compiles and burn the window).
    if python -c "
import importlib.util, sys
spec = importlib.util.spec_from_file_location('ks', 'scripts/kernel_sweep.py')
m = importlib.util.module_from_spec(spec); spec.loader.exec_module(m)
sys.exit(0 if m.aot_validated('pallas_fused') else 1)" 2>/dev/null; then
      run_step python scripts/kernel_sweep.py \
        scripts/plans/full_cross.json KERNELS_TPU.jsonl --timeout 900 --retries 1 \
        || failed=1
    fi
    # Regenerate the derived artifacts from whatever measurements exist
    # (CPU-only work; safe alongside the TPU being idle between steps).
    run_step python scripts/summarize_kernels.py || true
    run_step python -m distributed_sddmm_tpu.tools.charts \
      KERNELS_TPU.jsonl --kernels -o artifacts/kernels_chart || true
    commit_records "Queue: kernel-sweep TPU grid points + derived charts"
    if [ -n "$failed" ] && ! healthy_pallas; then continue; fi
    run_step timeout 1800 python scripts/dist_gap.py || true
    commit_records "Queue: tile-vs-distributed gap record"
    # Region-attribution breakdown on real hardware (round-4 stretch
    # directive): one run, resumable via the output file's existence.
    # Single chip forces c=1/nr=1, so Replication/Propagation are
    # STRUCTURALLY zero here — the run proves the attribution pipeline on
    # the target silicon; the c>1 bar shapes live in the CPU-mesh renders
    # (artifacts/cpu_mesh). The ablation variants compile on-device (they
    # are distinct programs the AOT caches don't cover), so re-gate Mosaic
    # first — dist_gap above may have outlived the service.
    if [ ! -f artifacts/tpu_breakdown/records.jsonl ]; then
      if ! healthy_pallas; then continue; fi
      mkdir -p artifacts/tpu_breakdown
      run_step timeout 2400 python -m distributed_sddmm_tpu.bench \
        er 14 32 15d_fusion2 128 1 --kernel pallas --trials 2 --breakdown \
        -o artifacts/tpu_breakdown/records.jsonl || failed=1
    fi
    if [ -f artifacts/tpu_breakdown/records.jsonl ]; then
      # Charts re-render every cycle like the other derived artifacts — a
      # one-time render failure must not be locked in by the guard above.
      run_step python -m distributed_sddmm_tpu.tools.charts \
        artifacts/tpu_breakdown/records.jsonl -o artifacts/tpu_breakdown \
        || true
    fi
    run_step timeout 7200 python scripts/tpu_apps.py \
      || { commit_records "Queue: partial TPU app/heatmap records"; sleep 300; continue; }
    commit_records "Queue: TPU app + heatmap records and breakdown"
    if [ -n "$failed" ]; then
      echo "[queue] sweep steps had failures; cycling to retry missing configs"
      sleep 300
      continue
    fi
    echo "[queue] all steps complete"
    break
  fi
  echo "[queue] $(date +%H:%M:%S) backend up, Mosaic down: XLA-only work"
  # A slower-but-real headline beats sweep points for the driver's
  # metric; bank it first in case the backend dies again.
  bank_headline 2400 xla
  commit_records "Queue: bank XLA-tier real-TPU headline record"
  run_step python scripts/kernel_sweep.py \
    scripts/plans/star_sweep.json KERNELS_TPU.jsonl --timeout 1200 --retries 1 \
    --kernel-filter xla \
    || { commit_records "Queue: partial XLA-tier sweep points"; sleep 300; continue; }
  run_step env APPS_XLA_ONLY=1 timeout 3600 python scripts/tpu_apps.py \
    || { commit_records "Queue: partial XLA-tier app records"; sleep 300; continue; }
  run_step python scripts/summarize_kernels.py || true
  run_step python -m distributed_sddmm_tpu.tools.charts \
    KERNELS_TPU.jsonl --kernels -o artifacts/kernels_chart || true
  commit_records "Queue: XLA-tier sweep + app records"
  echo "[queue] XLA-only steps complete; waiting for Mosaic recovery"
  sleep 600
done
