"""Serving-fleet smoke: chaos kill mid-load, zero wrong or lost replies.

The PR-16 acceptance demo on the CPU test mesh (a tier-1 test runs this
as a subprocess): ``bench fleet`` spawns serve-replica processes behind
the front router, drives an open-loop multi-tenant HTTP load, SIGKILLs
one replica at the load midpoint, and the judgment must hold:

* every 200 reply bit-identical (post-JSON) to the single-engine
  oracle — replica count, routing order, and the chaos kill must be
  invisible in the numbers;
* no reply lost: the killed replica's in-flight work is re-admitted by
  the router (failover) or shed WITH a Retry-After hint;
* the replacement replica warm-starts from the shared ProgramStore —
  0 request-path live compiles on generation ≥ 1;
* availability (ok + shed-with-retry + client-deferred)/offered stays
  above the floor;
* the record carries the fleet/tenant telemetry the gate reads
  (``fleet:availability``, per-tenant ``serve:burn_rate:*``).

Usage::

    python scripts/fleet_smoke.py [-o out.json]

Prints one JSON report; exit 0 when every check passes, 2 otherwise
(the 0/2 contract ``tests/test_fleet_smoke.py`` pins).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def exit_code(report: dict) -> int:
    """The smoke's exit contract: 0 all checks green, 2 otherwise."""
    return 0 if report.get("ok") else 2


def check_chaos_fleet(tmp: pathlib.Path) -> dict:
    """One ``bench fleet`` chaos run, then re-judge the record."""
    from distributed_sddmm_tpu.bench.cli import main as bench_main
    from distributed_sddmm_tpu.obs.regress import phase_stats

    out = tmp / "fleet.json"
    rc = bench_main([
        "fleet", "--replicas", "2", "--chaos", "kill-replica",
        "--duration", "5", "--rate", "12", "--log-m", "6", "--R", "8",
        "--no-runstore", "-o", str(out),
    ])
    records = [json.loads(line) for line in out.read_text().splitlines()]
    rec = records[-1] if records else {}
    fleet = rec.get("fleet") or {}
    axes = phase_stats({"record": rec})
    tenant = rec.get("tenant") or {}
    tenant_requests = sum(
        int(c.get("requests") or 0) for c in tenant.values()
    )
    return {
        "name": "chaos-fleet",
        "ok": bool(
            rc == 0
            and fleet.get("mismatches") == 0
            and fleet.get("lost") == 0
            and fleet.get("killed")
            and fleet.get("losses") == 1
            and fleet.get("replacement_live_compiles") == 0
            and (fleet.get("replacement_disk_hits") or 0) > 0
            and fleet.get("availability", 0.0)
            >= fleet.get("availability_floor", 0.95)
            and "fleet:availability" in axes
            # A SIGKILLed replica's recorder dies with it, so the
            # drained-record rollup may undercount the client's ok
            # tally by what the victim had served — never overcount,
            # and never lose the surviving replicas' attribution.
            and 0 < tenant_requests <= (fleet.get("ok") or 0)
        ),
        "exit_code": rc,
        "offered": fleet.get("offered"),
        "ok_replies": fleet.get("ok"),
        "mismatches": fleet.get("mismatches"),
        "lost": fleet.get("lost"),
        "killed": fleet.get("killed"),
        "availability": fleet.get("availability"),
        "replacement_live_compiles": fleet.get("replacement_live_compiles"),
        "replacement_disk_hits": fleet.get("replacement_disk_hits"),
        "gate_axes": sorted(
            k for k in axes if k.startswith(("fleet:", "serve:"))
        ),
        "tenant_requests": tenant_requests,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-o", "--output-file", default=None)
    args = ap.parse_args(argv)

    from distributed_sddmm_tpu.utils.platform import force_cpu_platform

    force_cpu_platform(n_devices=8, replace=True)

    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory() as tmpdir:
        checks = [check_chaos_fleet(pathlib.Path(tmpdir))]

    report = {
        "ok": all(c["ok"] for c in checks),
        "elapsed_s": round(time.perf_counter() - t0, 2),
        "checks": checks,
    }
    text = json.dumps(report, indent=1)
    print(text)
    if args.output_file:
        pathlib.Path(args.output_file).write_text(text)
    return exit_code(report)


if __name__ == "__main__":
    sys.exit(main())
