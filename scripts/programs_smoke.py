"""Program-store smoke: cross-process warm start, end to end.

The store's whole value proposition is that process B never compiles
what process A already built. This script is ONE of those processes: it
builds a plan-routed 1.5D dense-shift strategy bound to a program store
at ``--store``, dispatches one fused SDDMM→SpMM pair and one serving
ladder warmup, and reports the store counters as JSON. The tier-1 test
(``tests/test_programs_smoke.py``) runs it twice against one store
directory and pins the contract:

* process 1 (cold): ``live_compiles > 0``, ``program_store_hits == 0``;
* process 2 (warm): ``program_store_hits >= 1`` and
  ``live_compiles == 0`` for the warmed keys, with bit-identical
  outputs (the fused output fingerprint is part of the report).

Usage::

    python scripts/programs_smoke.py --store DIR [-o out.json]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--store", required=True, help="program-store root")
    ap.add_argument("-o", "--output-file", default=None)
    args = ap.parse_args()

    from distributed_sddmm_tpu.utils.platform import force_cpu_platform

    force_cpu_platform(n_devices=8, replace=True)

    import numpy as np

    from distributed_sddmm_tpu import programs
    from distributed_sddmm_tpu.autotune import Problem, get_plan
    from distributed_sddmm_tpu.autotune.cache import PlanCache
    from distributed_sddmm_tpu.common import MatMode
    from distributed_sddmm_tpu.models.als import DistributedALS
    from distributed_sddmm_tpu.obs import metrics as obs_metrics
    from distributed_sddmm_tpu.serve import ALSFoldInTopK, ServingEngine
    from distributed_sddmm_tpu.utils.coo import HostCOO

    store_root = pathlib.Path(args.store)
    store = programs.ProgramStore(store_root)
    plan_cache = PlanCache(store_root / "_plans")

    S = HostCOO.erdos_renyi(64, 48, 6, seed=0, values="normal")
    plan = get_plan(Problem.from_coo(S, 8), mode="model", cache=plan_cache)

    # --- plan-routed strategy program ------------------------------------
    alg = plan.instantiate(S, R=8, program_store=store)
    A = alg.dummy_initialize(MatMode.A)
    B = alg.dummy_initialize(MatMode.B)
    ones = alg.like_s_values(1.0)
    out, _mid = alg.fused_spmm(A, B, ones, MatMode.A)
    fused_fp = float(np.sum(np.asarray(out, dtype=np.float64) ** 2))

    # --- serving bucket ladder -------------------------------------------
    model = DistributedALS(alg, S_host=S)
    model.initialize_embeddings()
    workload = ALSFoldInTopK(model, k=3, item_buckets=(4, 8))
    engine = ServingEngine(
        workload, max_batch=2, max_depth=8, max_wait_ms=2.0,
        program_store=store,
    )
    warmed = engine.warmup()

    rep = {
        "ok": True,
        "plan": {"algorithm": plan.algorithm, "c": plan.c,
                 "key": plan.fingerprint_key},
        "fused_fingerprint": fused_fp,
        "ladder_cells": warmed,
        "engine": {k: engine.stats()[k]
                   for k in ("programs", "disk_hits", "live_compiles")},
        "store": store.stats(),
        "global": {
            k: obs_metrics.GLOBAL.get(k)
            for k in ("program_store_hits", "program_store_misses",
                      "live_compiles")
        },
        "entries_on_disk": len(list((store_root / "entries").glob("*.prog"))),
    }
    text = json.dumps(rep, indent=1)
    print(text)
    if args.output_file:
        pathlib.Path(args.output_file).write_text(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
