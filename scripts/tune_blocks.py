"""Block-size tuning probe for the Pallas chunk kernels (runs on real TPU).

For one (logM, nnz/row, R) config, times the fused/sddmm/spmm tile kernels
across (block_rows, block_cols) candidates plus the XLA gather kernel, and
prints one JSON line per measurement. Model for interpreting results:

    t_chunk ~ max(mxu: 2*R*CHUNK*(2*bm+bn)/PEAK, dma: bt block, fixed overhead)
    total   ~ n_chunks * t_chunk

When ``TUNE_LOAD_DIR`` is set, the chained-trial programs are NOT compiled
on-device: pre-serialized AOT executables (built offline by
`scripts/aot_compile_kernels.py`, validated by `scripts/aot_load_probe.py`)
are loaded onto the chip instead — same programs, same timing protocol
(`bench.aot.chain_time_loaded`), minutes of remote Mosaic compile saved per
config. Any load failure falls back to the on-device path.

Usage: python scripts/tune_blocks.py [logM npr R trials]
"""

from __future__ import annotations

import json
import sys

import numpy as np
import jax
import jax.numpy as jnp

from distributed_sddmm_tpu.ops.blocked import CHUNK, build_blocked
from distributed_sddmm_tpu.ops.kernels import XlaKernel
from distributed_sddmm_tpu.ops.pallas_kernels import BlockedTile, PallasKernel
from distributed_sddmm_tpu.utils.coo import HostCOO
from distributed_sddmm_tpu.bench.kernels import _chain_time

import os

BLOCKS = [(512, 512), (256, 512), (512, 1024), (256, 1024), (1024, 512),
          (1024, 1024), (256, 256), (128, 512)]
if os.environ.get("TUNE_BLOCKS"):
    BLOCKS = [tuple(int(x) for x in pair.split("x"))
              for pair in os.environ["TUNE_BLOCKS"].split(",")]
FUSED_ONLY = bool(os.environ.get("TUNE_FUSED_ONLY"))
SKIP_XLA = bool(os.environ.get("TUNE_SKIP_XLA"))
SCATTER_FORM = os.environ.get("TUNE_SCATTER", "bt")
BATCH_STEP = os.environ.get("TUNE_BATCH", "0") not in ("", "0")
LOAD_DIR = os.environ.get("TUNE_LOAD_DIR", "")


def build_inputs(log_m: int, npr: int, R: int):
    """Deterministic benchmark operands (shared with the offline AOT
    compiler, which only needs the shapes/dtypes to match)."""
    S = HostCOO.rmat(log_m=log_m, edge_factor=npr, seed=0)
    S = S.with_values(np.random.default_rng(1).standard_normal(S.nnz))
    rng = np.random.default_rng(0)
    A = jnp.array(rng.standard_normal((S.M, R)), jnp.float32)
    B = jnp.array(rng.standard_normal((S.N, R)), jnp.float32)
    return S, A, B, 2.0 * S.nnz * R


def build_blk(S, bm_pref: int, bn_pref: int, group: int):
    """Chunk-list metadata + device tile for one block preference.
    Returns (meta, blk, cvals); blk/cvals are None when pick_block clamped
    the preference (caller emits a tombstone)."""
    meta = build_blocked(1, np.zeros(S.nnz, np.int64), S.rows, S.cols,
                         S.M, S.N, block_rows=bm_pref, block_cols=bn_pref,
                         group=group)
    if (meta.bm, meta.bn) != (bm_pref, bn_pref):
        return meta, None, None
    blk = BlockedTile(
        lr=jnp.array(meta.lr[0]), lc=jnp.array(meta.lc[0]),
        meta=jnp.array(meta.meta[0]), bm=meta.bm, bn=meta.bn,
        gr_blocks=meta.gr_blocks, gc_blocks=meta.gc_blocks,
        group=meta.group,
    )
    vals_np = np.zeros(meta.n_chunks * CHUNK, np.float32)
    vals_np[meta.host_to_chunk] = S.vals
    return meta, blk, jnp.array(vals_np)


def xla_operands(S):
    """Device COO operands for the XLA kernel branch (single home for the
    dtype/shape conversion the offline AOT compiler must replicate)."""
    return (jnp.array(S.rows.astype(np.int32)),
            jnp.array(S.cols.astype(np.int32)),
            jnp.array(S.vals.astype(np.float32)))


def xla_steps(kern, rows, cols, vals, S, A) -> dict:
    """The XLA-kernel chained-trial step functions (shared with the
    offline AOT compiler). The moving operand B rides the chained state."""

    def sddmm_step(state):
        Bs, v = state
        out = kern.sddmm(rows, cols, v, A, Bs)
        return (Bs + out.sum() * 1e-30, v)

    def spmm_step(state):
        Bs, _ = state
        return (Bs + kern.spmm(rows, cols, vals, Bs, S.M)[: S.N] * 1e-12, _)

    return {"xla_sddmm": sddmm_step, "xla_spmm": spmm_step}


def pallas_steps(kernp, blk, cvals, S, A) -> dict:
    """The three chained-trial step functions (shared with the offline AOT
    compiler so the serialized programs are byte-identical in structure).
    The moving operand B is not closed over — it arrives via the chained
    state."""

    def fused_step(state):
        Bs, _ = state
        o, _mid = kernp.fused_tile(blk, cvals, A, Bs)
        return (Bs + o[: S.N] * 1e-12, _)

    def sddmm_step(state):
        Bs, v = state
        out = kernp.sddmm_tile(blk, v, A, Bs)
        return (Bs + out.sum() * 1e-30, v)

    def spmm_step(state):
        Bs, _ = state
        return (Bs + kernp.spmm_tile(blk, cvals, Bs, S.M)[: S.N] * 1e-12, _)

    return {"fused": fused_step, "sddmm": sddmm_step, "spmm": spmm_step}


def clamp_tombstone(log_m: int, npr: int, R: int, meta,
                    bm_pref: int, bn_pref: int) -> dict:
    """Timing-free record for a block preference pick_block clamped away.

    Carries the REQUESTED blocks (``blocks_req``) so kernel_sweep's resume
    key matches the plan config — without it the config re-runs (and
    "fails": zero output lines) on every queue cycle. Consumers drop it via
    the ``skipped`` field / the absent ``fused_pair_gflops``.
    """
    return {
        "kernel": "pallas-bf16", "logM": log_m, "npr": npr, "R": R,
        "blocks_req": f"{bm_pref}x{bn_pref}",
        "bm": meta.bm, "bn": meta.bn, "group": meta.group,
        "scatter_form": SCATTER_FORM, "chunk": CHUNK,
        "batch_step": BATCH_STEP, "skipped": "clamped",
    }


def _timed_op(op: str, step, state, trials: int) -> tuple[float, bool]:
    """Seconds per trial for one op, preferring the AOT-loaded executables
    when TUNE_LOAD_DIR holds this op's pair. ANY failure along the AOT
    path — load OR execution — falls back to the on-device jit; returns
    (seconds, used_aot)."""
    if LOAD_DIR:
        from distributed_sddmm_tpu.bench import aot

        try:
            loaded = aot.load_chain_pair(LOAD_DIR, op, trials,
                                         jax.devices()[0])
            return aot.chain_time_loaded(loaded, state, trials), True
        except Exception as e:  # noqa: BLE001 — any AOT failure -> jit path
            print(f"[tune] AOT path failed for {op} ({type(e).__name__}: "
                  f"{e}); falling back to on-device compile", file=sys.stderr)
    return _chain_time(step, state, trials), False


def main():
    log_m = int(sys.argv[1]) if len(sys.argv) > 1 else 16
    npr = int(sys.argv[2]) if len(sys.argv) > 2 else 32
    R = int(sys.argv[3]) if len(sys.argv) > 3 else 128
    trials = int(sys.argv[4]) if len(sys.argv) > 4 else 5

    S, A, B, flops = build_inputs(log_m, npr, R)

    if not SKIP_XLA:
        kern = XlaKernel()
        rows, cols, vals = xla_operands(S)
        steps = xla_steps(kern, rows, cols, vals, S, A)

        t_sddmm, aot_s = _timed_op("xla_sddmm", steps["xla_sddmm"],
                                   (B, vals), trials)
        t_spmm, aot_m = _timed_op("xla_spmm", steps["xla_spmm"],
                                  (B, vals), trials)
        rec = {"kernel": "xla", "logM": log_m, "npr": npr, "R": R,
               "aot": aot_s and aot_m,
               "sddmm_ms": t_sddmm * 1e3, "spmm_ms": t_spmm * 1e3,
               "sddmm_gflops": flops / t_sddmm / 1e9,
               "spmm_gflops": flops / t_spmm / 1e9,
               "fused_pair_gflops": 2 * flops / (t_sddmm + t_spmm) / 1e9}
        print(json.dumps(rec), flush=True)

    kernp = PallasKernel(scatter_form=SCATTER_FORM, batch_step=BATCH_STEP)
    for bm_pref, bn_pref in BLOCKS:
        group = int(os.environ.get("TUNE_GROUP", "1"))
        meta, blk, cvals = build_blk(S, bm_pref, bn_pref, group)
        if blk is None:
            print(json.dumps(
                clamp_tombstone(log_m, npr, R, meta, bm_pref, bn_pref)
            ), flush=True)
            continue
        steps = pallas_steps(kernp, blk, cvals, S, A)

        t_f, used_aot = _timed_op("fused", steps["fused"], (B, cvals), trials)
        t_s = t_m = None
        if not FUSED_ONLY:
            t_s, aot_s = _timed_op("sddmm", steps["sddmm"], (B, cvals), trials)
            t_m, aot_m = _timed_op("spmm", steps["spmm"], (B, cvals), trials)
            used_aot = used_aot and aot_s and aot_m
        occ = float((~meta.pad_lane).mean())
        rec = {"kernel": "pallas-bf16", "logM": log_m, "npr": npr, "R": R,
               "blocks_req": f"{bm_pref}x{bn_pref}",
               "bm": meta.bm, "bn": meta.bn, "n_chunks": meta.n_chunks,
               "group": meta.group, "scatter_form": SCATTER_FORM,
               "chunk": CHUNK, "batch_step": BATCH_STEP,
               "occupancy": round(occ, 3),
               "aot": used_aot,
               "fused_pair_ms": t_f * 1e3,
               "sddmm_ms": t_s and t_s * 1e3, "spmm_ms": t_m and t_m * 1e3,
               "fused_ns_per_chunk": t_f / meta.n_chunks * 1e9,
               "fused_pair_gflops": 2 * flops / t_f / 1e9,
               "sddmm_gflops": t_s and flops / t_s / 1e9,
               "spmm_gflops": t_m and flops / t_m / 1e9}
        print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
