"""Offline Mosaic compile pre-flight for planned kernel-sweep configs.

The tunneled TPU backend makes every on-device compile expensive (minutes)
and every hang costly (it eats a health window), but `artifacts/
multichip_hlo/run_pallas.py` established that the Mosaic/TPU compiler runs
LOCALLY against a `jax.experimental.topologies` target — no chips, no
tunnel. So before any plan config reaches `scripts/tpu_queue.sh`, this
validator AOT-compiles its exact Pallas kernel configuration (blocks,
group, chunk, scatter form, step batching, R) for a single v5e core on a
tiny R-mat and records ok / compile-error per config. A config that cannot
compile here cannot compile on the chip either (same compiler), so the
queue can skip it instead of timing out on it.

The reference has no analog (its kernels are prebuilt MKL/cuSPARSE calls,
`sparse_kernels.cpp:94-121`); this is tunnel-environment insurance.

Usage: python scripts/preflight_kernels.py [plan.json ...] [-o PREFLIGHT.json]
Defaults to every scripts/plans/*.json. Exit code 1 when any config fails.
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import importlib.util

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import topologies

# Compile for one core of the same generation the queue measures on.
TOPOLOGY = "v5e:2x4"

# The config-identity key is OWNED by the consumer (kernel_sweep skips
# failed keys); importing it keeps producer and consumer from drifting.
_ks_spec = importlib.util.spec_from_file_location(
    "kernel_sweep", pathlib.Path(__file__).with_name("kernel_sweep.py"))
_ks = importlib.util.module_from_spec(_ks_spec)
_ks_spec.loader.exec_module(_ks)
preflight_key = _ks.preflight_key


def pallas_configs(plans: list[pathlib.Path]) -> list[dict]:
    seen, out = set(), []
    for plan in plans:
        for cfg in json.loads(plan.read_text()):
            if cfg.get("kernel") != "pallas":
                continue
            key = preflight_key(cfg)
            if key in seen:
                continue
            seen.add(key)
            out.append({"plan": plan.name, **cfg})
    return out


def compile_one(cfg: dict, device) -> dict:
    """AOT-compile fused/sddmm/spmm tile kernels for one config; tiny
    graph, real (blocks, group, chunk, scatter, batch, R) knobs."""
    # Chunk size is snapshotted at import inside ops.blocked, so configs
    # with a non-default chunk run in a fresh subprocess (see main()).
    from distributed_sddmm_tpu.ops.blocked import CHUNK, build_blocked
    from distributed_sddmm_tpu.ops.pallas_kernels import BlockedTile, PallasKernel
    from distributed_sddmm_tpu.utils.coo import HostCOO

    assert CHUNK == cfg.get("chunk", 128), (CHUNK, cfg)
    bm, bn = (int(x) for x in cfg.get("blocks", "512x512").split("x"))
    R, group = cfg["R"], cfg.get("group", 1)

    S = HostCOO.rmat(log_m=11, edge_factor=8, seed=0)
    meta = build_blocked(1, np.zeros(S.nnz, np.int64), S.rows, S.cols,
                         S.M, S.N, block_rows=bm, block_cols=bn, group=group)
    # A clamped probe would validate a DIFFERENT kernel than the plan's and
    # record a false 'ok' for the unclamped key; fail loudly instead (the
    # probe matrix must be enlarged, or the plan config is one tune_blocks
    # would tombstone anyway).
    if (meta.bm, meta.bn) != (bm, bn):
        raise RuntimeError(
            f"probe clamped blocks {bm}x{bn} -> {meta.bm}x{meta.bn}; "
            f"preflight cannot vouch for this config")
    sharding = jax.sharding.SingleDeviceSharding(device)

    def sds(shape, dtype=jnp.float32):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)

    blk = BlockedTile(
        lr=sds(meta.lr[0].shape, jnp.int32), lc=sds(meta.lc[0].shape, jnp.int32),
        meta=sds(meta.meta[0].shape, jnp.int32), bm=meta.bm, bn=meta.bn,
        gr_blocks=meta.gr_blocks, gc_blocks=meta.gc_blocks, group=meta.group,
    )
    kern = PallasKernel(precision="bf16", interpret=False,
                        scatter_form=cfg.get("scatter", "bt"),
                        batch_step=bool(cfg.get("batch")))
    cvals = sds((meta.n_chunks * CHUNK,))
    A, B = sds((S.M, R)), sds((S.N, R))
    rows_pad = meta.gr_blocks * meta.bm

    report = {}
    # blk is a pytree of ShapeDtypeStructs, so it must flow through lower()
    # as an argument, not a closure constant. All three ops compile
    # regardless of the plan's fused_only flag: the preflight key has no
    # fused_only axis, so a fused-only probe config would otherwise mask
    # the full config sharing its key.
    ops = {
        "fused": lambda: jax.jit(kern.fused_tile).lower(blk, cvals, A, B),
        "sddmm": lambda: jax.jit(kern.sddmm_tile).lower(blk, cvals, A, B),
        "spmm": lambda: jax.jit(
            kern.spmm_tile, static_argnums=3
        ).lower(blk, cvals, B, rows_pad),
    }
    for name, build in ops.items():
        t0 = time.monotonic()
        build().compile()
        report[f"{name}_compile_s"] = round(time.monotonic() - t0, 2)
    return report


# The r_split far corner: the reference sweeps R to 4096
# (`local_kernel_benchmark.cpp:278`); full-R one-hot blocks cannot compile
# there (the configs report proves it), and the DESIGNED escape is the
# 1.5D sparse-shift feature split (`15D_sparse_shift.hpp:139-157` analog):
# per-device kernels see R*c/p columns. This compiles the blocked Mosaic
# programs of that path for the full 8-device v5e topology, proving the
# prescribed grid's far corner is reachable by design.
RSPLIT_CFG = {"R": 4096, "c": 1, "logM": 13, "npr": 8}


def compile_rsplit(cfg: dict) -> dict:
    """AOT-compile the blocked 1.5D sparse-shift sddmm+spmm programs (the
    fused pair chains exactly these two, `distributed_sparse.h:296-312`)
    over the v5e:2x4 topology mesh at per-device R-slices."""
    jax.config.update("jax_platforms", "cpu")
    from distributed_sddmm_tpu.common import MatMode
    from distributed_sddmm_tpu.ops.pallas_kernels import PallasKernel
    from distributed_sddmm_tpu.parallel.mesh import make_grid
    from distributed_sddmm_tpu.parallel.sparse_shift_15d import SparseShift15D
    from distributed_sddmm_tpu.utils.coo import HostCOO

    cpu = jax.devices()
    assert len(cpu) >= 8, "needs XLA_FLAGS=--xla_force_host_platform_device_count=8"
    R, c = cfg["R"], cfg["c"]
    topo = topologies.get_topology_desc(platform="tpu", topology_name=TOPOLOGY)
    S = HostCOO.rmat(log_m=cfg["logM"], edge_factor=cfg["npr"], seed=0)
    # Ingest on the CPU mesh with the interpret kernel (builds the blocked
    # chunk-list metadata), then retarget the topology mesh with the real
    # Mosaic kernel — the run_pallas.py census pattern.
    alg = SparseShift15D(S, R, c=c, devices=cpu[:8],
                         kernel=PallasKernel(precision="f32", interpret=True))
    A = alg.dummy_initialize(MatMode.A)
    B = alg.dummy_initialize(MatMode.B)
    vals = alg.like_s_values(1.0)
    g = alg.grid
    alg.grid = make_grid(g.nr, g.nc, g.nh, adjacency=g.adjacency,
                         devices=list(topo.devices))
    alg.kernel = PallasKernel(precision="bf16", interpret=False)
    alg._programs.clear()
    mesh = alg.grid.mesh

    def sds_like(x):
        return jax.ShapeDtypeStruct(
            x.shape, x.dtype,
            sharding=jax.sharding.NamedSharding(mesh, x.sharding.spec))

    bm, bn, *_ = alg.S_tiles.blk_geom
    rec = {**cfg, "p": 8, "r_local": R * c // 8, "blocks": f"{bm}x{bn}",
           "strategy": "15d_sparse", "kernel": "pallas-bf16 blocked",
           "topology": TOPOLOGY}
    for op, call_args in (
        ("sddmm", (A, B, *alg._sddmm_args(alg.S_tiles, vals))),
        ("spmm", (B, *alg._spmm_args(alg.S_tiles, vals))),
    ):
        t0 = time.monotonic()
        prog = alg._program(op, False)
        compiled = prog.lower(*(sds_like(a) for a in call_args)).compile()
        rec[f"{op}_compile_s"] = round(time.monotonic() - t0, 2)
        rec[f"{op}_mosaic_calls"] = compiled.as_text().count(
            'custom_call_target="tpu_custom_call"')
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("plans", nargs="*", help="plan JSONs (default: scripts/plans/*)")
    ap.add_argument("-o", "--output", default=str(REPO / "PREFLIGHT.json"))
    ap.add_argument("--config-json", default=None,
                    help="(internal) compile ONE config, passed as JSON")
    ap.add_argument("--rsplit-json", default=None,
                    help="(internal) compile the r_split programs, cfg as JSON")
    args = ap.parse_args(argv)

    if args.rsplit_json:
        print(json.dumps(compile_rsplit(json.loads(args.rsplit_json))))
        return 0

    if args.config_json:
        # Pure AOT work — pin the default backend to CPU so nothing can
        # reach for the tunnel even in environments that export
        # JAX_PLATFORMS for it (env var alone is ignored here because
        # sitecustomize pre-imports jax; the config update is not).
        jax.config.update("jax_platforms", "cpu")
        cfg = json.loads(args.config_json)
        topo = topologies.get_topology_desc(platform="tpu", topology_name=TOPOLOGY)
        print(json.dumps(compile_one(cfg, topo.devices[0])))
        return 0

    plans = [pathlib.Path(p) for p in args.plans] or sorted(
        (REPO / "scripts" / "plans").glob("*.json"))
    configs = pallas_configs(plans)
    results, failures = [], 0
    out_path = pathlib.Path(args.output)

    # Carry forward prior results for configs this run hasn't reached yet:
    # an outer timeout must not discard the committed report's knowledge
    # (each fresh result replaces its key as the run progresses).
    old_by_key = {}
    rsplit_state = {}
    try:
        old_report = json.loads(out_path.read_text())
        for rec in old_report.get("configs", []):
            old_by_key[preflight_key(rec)] = rec
        rsplit_state = old_report.get("r_split") or {}
    except (OSError, json.JSONDecodeError, KeyError):
        pass

    def flush_report():
        fresh = {preflight_key(r) for r in results}
        merged = results + [r for k, r in old_by_key.items() if k not in fresh]
        out = {"topology": TOPOLOGY,
               "note": "offline Mosaic AOT compile check; a compile-error "
                       "here means the queue would hang/fail on this config",
               "complete": len(results) == len(configs),
               "configs": merged}
        if rsplit_state:
            out["r_split"] = rsplit_state
        # Atomic replace: an outer SIGTERM mid-write must not truncate the
        # report (a broken JSON disables all preflight skipping AND
        # clobbers the committed known-good file).
        tmp = out_path.with_suffix(".tmp")
        tmp.write_text(json.dumps(out, indent=1))
        os.replace(tmp, out_path)

    import subprocess

    def run_config(cfg: dict):
        env = dict(os.environ)
        env["DSDDMM_CHUNK"] = str(cfg.get("chunk", 128))
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = f"{REPO}:{env.get('PYTHONPATH', '')}"
        return subprocess.run(
            [sys.executable, __file__, "--config-json", json.dumps(cfg)],
            env=env, capture_output=True, text=True, timeout=600)

    # Canary: the long-measured headline config must compile. If it does
    # not, the AOT environment itself is broken (jax upgrade, missing
    # libtpu AOT, ...) and NO failure this run can be trusted as
    # config-specific — bail without poisoning the report.
    canary = {"logM": 14, "npr": 32, "R": 128, "kernel": "pallas",
              "blocks": "512x512", "group": 4}
    try:
        cp = run_config(canary)
    except subprocess.TimeoutExpired:
        cp = None
    if cp is None or cp.returncode != 0:
        tail = "" if cp is None else "\n".join(
            cp.stderr.strip().splitlines()[-8:])
        print("[preflight] CANARY FAILED — AOT environment broken, "
              f"leaving existing report untouched\n{tail}", file=sys.stderr)
        return 3

    for cfg in configs:
        t0 = time.monotonic()
        rec = {k: cfg.get(k) for k in
               ("plan", "logM", "npr", "R", "blocks", "group", "chunk",
                "scatter", "batch", "fused_only")}
        try:
            proc = run_config(cfg)
        except subprocess.TimeoutExpired:
            # One hanging compile must not lose the whole report — record
            # it and move on. NOTE: a timeout is not proof of
            # uncompilability, so kernel_sweep deliberately does NOT skip
            # these (only status == "compile-error"); the nonzero exit
            # here just flags that preflight could not vouch for
            # everything.
            proc = None
        rec["wall_s"] = round(time.monotonic() - t0, 1)
        if proc is None:
            rec.update(status="timeout")
            failures += 1
        elif proc.returncode == 0:
            try:
                rec.update(status="ok", **json.loads(
                    proc.stdout.strip().splitlines()[-1]))
            except (json.JSONDecodeError, IndexError):
                rec.update(status="bad-output", stderr=proc.stderr[-800:])
                failures += 1
        else:
            stderr_full = proc.stderr or ""
            tail = "\n".join(stderr_full.strip().splitlines()[-12:])
            # A clamped probe means this probe matrix cannot represent the
            # config — NOT that the config can't compile at its real grid
            # size; a libtpu lockfile/busy clash means another local
            # process held the TPU plugin (e.g. a concurrent preflight) —
            # both get statuses failed_preflight_keys ignores, so neither
            # can ever blacklist a measurable config. Classify on the FULL
            # stderr (the signature can scroll above the stored tail).
            if "preflight cannot vouch" in stderr_full:
                status = "probe-invalid"
            elif ("libtpu_lockfile" in stderr_full
                  or "already in use" in stderr_full):
                status = "env-transient"
            else:
                status = "compile-error"
            rec.update(status=status, error=tail)
            failures += 1
        # Transient outcomes (lockfile clash, timeout) are not evidence
        # about the CONFIG — they must not clobber a committed ok record
        # (e.g. a concurrent prewarm holding the libtpu lock would
        # otherwise downgrade the whole report to env-transient). The
        # config then no longer counts as failed: its record IS ok.
        old = old_by_key.get(preflight_key(rec))
        if (rec["status"] in ("env-transient", "timeout")
                and old is not None and old.get("status") == "ok"):
            print(f"[preflight] {rec['status']} for R={cfg['R']} "
                  f"blocks={cfg.get('blocks', '512x512')}; keeping the "
                  "prior ok record", flush=True)
            rec = old
            failures -= 1
        results.append(rec)
        flush_report()
        print(f"[preflight] {rec['status']:13s} "
              f"R={cfg['R']} blocks={cfg.get('blocks', '512x512')} "
              f"g={cfg.get('group', 1)} chunk={cfg.get('chunk', 128)} "
              f"scatter={cfg.get('scatter', 'bt')} batch={bool(cfg.get('batch'))} "
              f"({rec['wall_s']}s)", flush=True)

    # Per-config tally frozen here: the r_split outcome below feeds the
    # exit code but must not misattribute its failure to the config list.
    cfg_failures = failures

    # r_split far-corner proof (resumable: a matching ok record stands).
    rsplit_current = (rsplit_state.get("status") == "ok"
                      and all(rsplit_state.get(k) == v
                              for k, v in RSPLIT_CFG.items()))
    if not rsplit_current:
        t0 = time.monotonic()
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = f"{REPO}:{env.get('PYTHONPATH', '')}"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8").strip()
        try:
            proc = subprocess.run(
                [sys.executable, __file__, "--rsplit-json",
                 json.dumps(RSPLIT_CFG)],
                env=env, capture_output=True, text=True, timeout=900)
        except subprocess.TimeoutExpired:
            proc = None
        wall = round(time.monotonic() - t0, 1)
        if proc is not None and proc.returncode == 0:
            try:
                rsplit_state = {"status": "ok", "wall_s": wall, **json.loads(
                    proc.stdout.strip().splitlines()[-1])}
            except (json.JSONDecodeError, IndexError):
                rsplit_state = {"status": "bad-output", "wall_s": wall,
                                **RSPLIT_CFG, "stderr": proc.stderr[-800:]}
                failures += 1
        else:
            tail = "timeout" if proc is None else "\n".join(
                (proc.stderr or "").strip().splitlines()[-12:])
            rsplit_state = {"status": "compile-error", "wall_s": wall,
                            **RSPLIT_CFG, "error": tail}
            failures += 1
        flush_report()
        print(f"[preflight] r_split {rsplit_state['status']} "
              f"R={RSPLIT_CFG['R']} c={RSPLIT_CFG['c']} "
              f"r_local={RSPLIT_CFG['R'] * RSPLIT_CFG['c'] // 8} "
              f"({wall}s)", flush=True)

    print(f"[preflight] {len(results) - cfg_failures}/{len(results)} "
          f"configs ok, r_split {rsplit_state.get('status', '?')} "
          f"-> {out_path}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
