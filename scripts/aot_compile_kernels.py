"""Offline AOT compiler for one kernel-sweep config's chained programs.

Companion to `scripts/tune_blocks.py`'s TUNE_LOAD_DIR mode: builds the SAME
step functions (imported from tune_blocks, so program structure cannot
drift), AOT-compiles their chained-trial pairs against a v5e topology
device — locally, no tunnel — and serializes them for the TPU worker to
load. Driven by `scripts/kernel_sweep.py` when AOT_LOAD.json records that
re-homed loads work on this backend.

Runs CPU-pinned; only shapes/dtypes of the operands matter here.

Usage: python scripts/aot_compile_kernels.py logM npr R trials OUT_DIR
Env knobs: identical to tune_blocks (TUNE_BLOCKS single pair, TUNE_GROUP,
TUNE_SCATTER, TUNE_BATCH, TUNE_FUSED_ONLY, DSDDMM_CHUNK).
"""

from __future__ import annotations

import importlib.util
import json
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

import jax

jax.config.update("jax_platforms", "cpu")

from jax.experimental import topologies

TOPOLOGY = "v5e:2x4"


def main() -> int:
    log_m, npr, R, trials = (int(x) for x in sys.argv[1:5])
    out_dir = pathlib.Path(sys.argv[5])

    spec = importlib.util.spec_from_file_location(
        "tune_blocks", pathlib.Path(__file__).with_name("tune_blocks.py"))
    tune = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tune)

    from distributed_sddmm_tpu.bench import aot
    from distributed_sddmm_tpu.ops.pallas_kernels import PallasKernel

    import os

    if os.environ.get("AOTC_KERNEL", "pallas") == "xla":
        # The flat XLA-kernel chains (tune_blocks' non-Pallas branch).
        from distributed_sddmm_tpu.ops.kernels import XlaKernel

        S, A, B, _flops = tune.build_inputs(log_m, npr, R)
        kern = XlaKernel()
        rows, cols, vals = tune.xla_operands(S)
        steps = tune.xla_steps(kern, rows, cols, vals, S, A)
        topo = topologies.get_topology_desc(platform="tpu",
                                            topology_name=TOPOLOGY)
        report = {"ok": True, "kernel": "xla", "compile_s": {}}
        for name, step in steps.items():
            report["compile_s"][name] = aot.compile_chain_pair(
                step, (B, vals), trials, topo.devices[0], out_dir, name)
        (out_dir / "meta.json").write_text(json.dumps(report, indent=1))
        print(json.dumps(report))
        return 0

    if len(tune.BLOCKS) != 1:
        print("aot_compile_kernels expects exactly one TUNE_BLOCKS pair",
              file=sys.stderr)
        return 1
    bm_pref, bn_pref = tune.BLOCKS[0]
    group = int(os.environ.get("TUNE_GROUP", "1"))
    S, A, B, _flops = tune.build_inputs(log_m, npr, R)
    meta, blk, cvals = tune.build_blk(S, bm_pref, bn_pref, group)
    if blk is None:
        # tune_blocks will emit the tombstone itself; cache the negative so
        # kernel_sweep doesn't re-run this subprocess on every resume.
        out_dir.mkdir(parents=True, exist_ok=True)
        (out_dir / "meta.json").write_text(
            json.dumps({"ok": False, "reason": "clamped"}))
        print(json.dumps({"ok": False, "reason": "clamped"}))
        return 0
    # The on-device worker runs bf16 Mosaic kernels; compile exactly that.
    kernp = PallasKernel(precision="bf16", interpret=False,
                         scatter_form=tune.SCATTER_FORM,
                         batch_step=tune.BATCH_STEP)

    steps = tune.pallas_steps(kernp, blk, cvals, S, A)

    topo = topologies.get_topology_desc(platform="tpu", topology_name=TOPOLOGY)
    dev = topo.devices[0]
    ops = ["fused"] if tune.FUSED_ONLY else ["fused", "sddmm", "spmm"]
    report = {"ok": True, "config": {
        "logM": log_m, "npr": npr, "R": R, "trials": trials,
        "blocks": f"{bm_pref}x{bn_pref}", "group": group,
        "scatter": tune.SCATTER_FORM, "batch": tune.BATCH_STEP,
        "chunk": tune.CHUNK}, "compile_s": {}}
    for op in ops:
        times = aot.compile_chain_pair(
            steps[op], (B, cvals), trials, dev, out_dir, op)
        report["compile_s"][op] = times
    (out_dir / "meta.json").write_text(json.dumps(report, indent=1))
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
