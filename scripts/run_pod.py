"""Multi-host TPU pod runner (thin wrapper).

The pod wiring — coordinator resolution, ``jax.distributed`` init,
per-worker admin ports, per-worker trace shards, end-of-run pod
timeline merge — lives in :mod:`distributed_sddmm_tpu.dist.run` since
PR 14 (it used to live here); this script remains the operational entry
point the runbook invokes on every host:

    gcloud compute tpus tpu-vm ssh $TPU_NAME --worker=all \
      --command="cd ~/distributed_sddmm_tpu && python scripts/run_pod.py \
                 er 20 32 15d_fusion2 128 4 -o results.jsonl"

JAX's TPU backend discovers coordinator/topology automatically on Cloud
TPU; pass --coordinator (or DSDDMM_DIST_COORDINATOR) for other clusters.
"""

from __future__ import annotations

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from distributed_sddmm_tpu.dist.run import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
