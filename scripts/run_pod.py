"""Multi-host TPU pod runner.

The reference scaled with SLURM jobscripts over MPI ranks
(`/root/reference/jobscript.sh`); on TPU pods the analog is one process per
host, connected by ``jax.distributed.initialize()``, with every algorithm in
this framework unchanged — the 3-D ``Mesh`` simply spans all pod chips and
the shift/replication axes ride ICI (and DCN across slices).

Run THIS SAME script on every host of the pod, e.g. with

    gcloud compute tpus tpu-vm ssh $TPU_NAME --worker=all \
      --command="cd ~/distributed_sddmm_tpu && python scripts/run_pod.py \
                 er 20 32 15d_fusion2 128 4 -o results.jsonl"

JAX's TPU backend discovers coordinator/topology automatically on Cloud TPU;
pass --coordinator for other clusters.
"""

from __future__ import annotations

import argparse
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--coordinator", default=None,
                    help="host:port (omit on Cloud TPU: auto-discovered)")
    ap.add_argument("--num-processes", type=int, default=None)
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("--dry-run", action="store_true",
                    help="print the resolved initialize()/bench invocation "
                    "and exit (testable without a pod)")
    ap.add_argument("bench_args", nargs=argparse.REMAINDER,
                    help="arguments forwarded to distributed_sddmm_tpu.bench")
    args = ap.parse_args(argv)

    if args.coordinator is None and (
        args.num_processes is not None or args.process_id is not None
    ):
        ap.error("--num-processes/--process-id require --coordinator "
                 "(without it, Cloud TPU auto-discovery ignores them)")
    init_kwargs = (
        dict(coordinator_address=args.coordinator,
             num_processes=args.num_processes, process_id=args.process_id)
        if args.coordinator else {}
    )
    if args.dry_run:
        # Validate the forwarded bench arguments parse, without touching any
        # backend or coordinator.
        from distributed_sddmm_tpu.bench.cli import build_parser

        build_parser().parse_args(args.bench_args)
        print(f"dry-run ok: initialize({init_kwargs}) -> bench {args.bench_args}")
        return 0

    import jax

    jax.distributed.initialize(**init_kwargs)  # Cloud TPU: auto-discovery

    if jax.process_index() == 0:
        print(
            f"pod up: {jax.process_count()} hosts, "
            f"{jax.device_count()} chips ({jax.local_device_count()}/host)"
        )

    from distributed_sddmm_tpu.bench.cli import main as bench_main

    return bench_main(args.bench_args)


if __name__ == "__main__":
    sys.exit(main())
