"""CPU-mesh observability smoke: trace, manifest, counters end to end.

Runs a tiny traced + fault-injected ALS bench on the same virtual
8-device CPU mesh the test suite uses, then validates everything the
observability layer promised (fast enough for CI; a tier-1 test runs
this as a subprocess):

1. **schema** — every emitted trace line parses and validates against
   the v1 schema (``tools/tracereport.validate_record``), and the run
   manifest exists with the required fields.
2. **attribution** — the injected fault's retry shows up as overhead
   seconds on the faulted op, separated from kernel seconds, and the
   fault + retry events appear in the trace.
3. **comm agreement** — the counted per-device comm words for the
   fused-pair ops match ``tools/costmodel.pair_words`` for the chosen
   strategy (the paper's measured-vs-modeled volume check).
4. **disabled overhead** — with tracing off, the per-dispatch hook cost
   (span() + metrics bookkeeping) stays in the microsecond range, far
   under the <2% bench budget (best-of-N so a loaded CI machine's
   scheduling noise cannot trip it).
5. **regression gate** — the `bench gate` CLI judges two synthetic runs
   in a throwaway store: a within-noise rerun passes (exit 0) and a 2x
   slowdown fails (exit 2) — the cross-run half of the obs layer, CPU
   only, no benchmark execution.

Usage::

    python scripts/obs_smoke.py [--devices 8] [-o out.json]

Prints one JSON summary; exits nonzero if any check fails.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def run_traced_bench(tmp: str) -> dict:
    """One traced, fault-injected ALS bench run; returns paths + record."""
    from distributed_sddmm_tpu.bench.harness import benchmark_algorithm
    from distributed_sddmm_tpu.obs import trace
    from distributed_sddmm_tpu.resilience import FaultPlan, FaultSpec, fault_plan
    from distributed_sddmm_tpu.utils.coo import HostCOO

    tr = trace.enable(pathlib.Path(tmp) / "traces")
    S = HostCOO.rmat(log_m=6, edge_factor=8, seed=0)
    plan = FaultPlan([
        FaultSpec(site="execute:cgStep", kind="timeout", at=(1,)),
    ])
    with fault_plan(plan):
        record = benchmark_algorithm(
            S, "15d_fusion2", None, fused=True, R=16, c=2,
            app="als", trials=2, warmup=0,
        )
    trace.disable()
    return {
        "record": record,
        "trace_path": str(tr.path),
        "fault_events": len(plan.events),
    }


def check_schema(trace_path: str, record: dict) -> dict:
    from distributed_sddmm_tpu.obs import manifest as mani
    from distributed_sddmm_tpu.tools import tracereport

    try:
        trace = tracereport.load_trace(trace_path, strict=True)
        schema_ok = True
        schema_err = None
    except ValueError as e:
        trace = tracereport.load_trace(trace_path, strict=False)
        schema_ok, schema_err = False, str(e)

    man = tracereport.load_manifest(trace_path)
    man_ok = bool(
        man
        and man.get("schema") == mani.SCHEMA_VERSION
        and man.get("run_id")
        and "env" in man
    )
    record_linked = (
        record.get("run_id") == (trace["begin"] or {}).get("run_id")
        and record.get("trace_path") == trace_path
    )
    return {
        "name": "schema",
        "ok": bool(schema_ok and man_ok and record_linked),
        "spans": len(trace["spans"]),
        "events": len(trace["events"]),
        "schema_error": schema_err,
        "manifest_ok": man_ok,
        "record_linked": record_linked,
    }


def check_attribution(trace_path: str, record: dict, fired: int) -> dict:
    from distributed_sddmm_tpu.tools import tracereport

    trace = tracereport.load_trace(trace_path, strict=False)
    report = tracereport.aggregate(trace)
    cg = report["phases"].get("cgStep", {})
    ev = report["events"]
    metrics_cg = record.get("metrics", {}).get("cgStep", {})
    return {
        "name": "attribution",
        "ok": bool(
            fired >= 1
            and ev.get("fault_fired", 0) >= 1
            and ev.get("retry", 0) >= 1
            and cg.get("retries", 0) >= 1
            and cg.get("overhead_s", 0.0) > 0.0
            and cg.get("kernel_s", 0.0) > 0.0
            and metrics_cg.get("retries", 0) >= 1
            and metrics_cg.get("overhead_s", 0.0) > 0.0
        ),
        "cg_kernel_s": round(cg.get("kernel_s", 0.0), 4),
        "cg_overhead_s": round(cg.get("overhead_s", 0.0), 4),
        "fault_events": ev.get("fault_fired", 0),
        "retry_events": ev.get("retry", 0),
    }


def check_comm_agreement(trace_path: str) -> dict:
    from distributed_sddmm_tpu.tools import tracereport

    trace = tracereport.load_trace(trace_path, strict=False)
    report = tracereport.aggregate(trace)
    checked, ok = 0, True
    for name in ("cgStep", "fusedSpMM"):
        ph = report["phases"].get(name)
        if not ph or "model_words" not in ph:
            continue
        checked += 1
        if ph["model_words"] > 0:
            ok &= abs(ph["comm_words"] - ph["model_words"]) <= (
                1e-6 * ph["model_words"]
            )
        else:
            ok &= ph["comm_words"] == 0
    return {
        "name": "comm_agreement",
        "ok": bool(ok and checked >= 1),
        "ops_checked": checked,
    }


def check_disabled_overhead(reps: int = 5) -> dict:
    """The disabled-tracer hook cost per dispatch, measured directly.

    Best-of-``reps``: the hook cost is a *capability* bound (can the
    disabled path run this fast), so the minimum over several repeats is
    the right statistic — a single-shot mean conflates the hooks with
    whatever else a loaded CI machine scheduled mid-loop, which made
    this check flaky."""
    from distributed_sddmm_tpu.obs import metrics, trace

    assert not trace.enabled()
    n = 20000
    om = metrics.OpMetrics()
    samples_us = []
    for _ in range(max(reps, 1)):
        t0 = time.perf_counter()
        for _ in range(n):
            sp = trace.span("x")  # the per-dispatch disabled-path hooks
            om.record("x", 1e-6, comm_words=1.0, flops=1.0)
        samples_us.append((time.perf_counter() - t0) / n * 1e6)
    per_call_us = min(samples_us)
    return {
        "name": "disabled_overhead",
        # Generous CI bound: the real budget is <2% of a bench whose
        # dispatches cost milliseconds; 50us/call would still pass that.
        "ok": bool(sp is trace.NOOP_SPAN and per_call_us < 50.0),
        "per_call_us": round(per_call_us, 3),
        "samples_us": [round(s, 3) for s in samples_us],
    }


def _synth_run_doc(run_id: str, scale: float) -> dict:
    """A minimal comparable run document: one fused-pair phase whose
    seconds scale by ``scale`` (1.0 = baseline speed)."""
    kernel_s = 0.100 * scale
    return {
        "run_id": run_id,
        "key": "smoke-synthetic-key",
        "backend": "cpu",
        "code_hash": "smoke",
        "source": "obs_smoke",
        "record": {
            "algorithm": "15d_fusion2", "app": "vanilla",
            "R": 128, "c": 1, "fused": True,
            "elapsed": kernel_s, "overall_throughput": 4.0 / kernel_s,
            "metrics": {
                "fusedSpMM": {
                    "calls": 10, "kernel_s": kernel_s, "overhead_s": 0.0,
                    "retries": 0, "comm_words": 1.0e6,
                    "comm_words_extra": 0.0, "flops": 4.0e9,
                },
            },
        },
    }


def check_regression_gate(tmp: str) -> dict:
    """Drive the real `bench gate` subcommand over a throwaway store."""
    import contextlib
    import io

    from distributed_sddmm_tpu.bench import cli
    from distributed_sddmm_tpu.obs.store import RunStore

    def gate(run_id: str, root: str) -> int:
        # Capture the CLI's human tables: this script's own stdout is a
        # single JSON report and must stay machine-parseable. SystemExit
        # (unknown run) maps to its code rather than killing the smoke.
        with contextlib.redirect_stdout(io.StringIO()):
            try:
                return cli.main(["gate", run_id, "--store", root])
            except SystemExit as e:
                return int(e.code) if isinstance(e.code, int) else 1

    root = str(pathlib.Path(tmp) / "runstore")
    store = RunStore(root)
    store.put(_synth_run_doc("base-1", 1.00))
    store.put(_synth_run_doc("base-2", 0.99))
    store.put(_synth_run_doc("rerun-ok", 1.03))     # within the ±15% band
    rc_ok = gate("rerun-ok", root)
    store.put(_synth_run_doc("rerun-slow", 2.00))   # a 2x slowdown
    rc_slow = gate("rerun-slow", root)
    return {
        "name": "regression_gate",
        "ok": bool(rc_ok == 0 and rc_slow == 2),
        "within_noise_exit": rc_ok,
        "slowdown_exit": rc_slow,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("-o", "--output-file", default=None)
    args = ap.parse_args(argv)

    from distributed_sddmm_tpu.utils.platform import force_cpu_platform

    force_cpu_platform(n_devices=args.devices, replace=True)

    checks = []
    with tempfile.TemporaryDirectory() as tmp:
        try:
            run = run_traced_bench(tmp)
            checks.append(check_schema(run["trace_path"], run["record"]))
            checks.append(check_attribution(
                run["trace_path"], run["record"], run["fault_events"]
            ))
            checks.append(check_comm_agreement(run["trace_path"]))
        except Exception as e:  # noqa: BLE001 — a smoke run reports
            checks.append({
                "name": "traced_bench", "ok": False,
                "error": f"{type(e).__name__}: {e}",
            })
        try:
            checks.append(check_disabled_overhead())
        except Exception as e:  # noqa: BLE001
            checks.append({
                "name": "disabled_overhead", "ok": False,
                "error": f"{type(e).__name__}: {e}",
            })
        try:
            checks.append(check_regression_gate(tmp))
        except Exception as e:  # noqa: BLE001
            checks.append({
                "name": "regression_gate", "ok": False,
                "error": f"{type(e).__name__}: {e}",
            })

    ok = all(c["ok"] for c in checks)
    out = {"ok": ok, "devices": args.devices, "checks": checks}
    blob = json.dumps(out, indent=1)
    print(blob)
    if args.output_file:
        with open(args.output_file, "w") as f:
            f.write(blob + "\n")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
