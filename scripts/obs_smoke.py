"""CPU-mesh observability smoke: trace, manifest, counters end to end.

Runs a tiny traced + fault-injected ALS bench on the same virtual
8-device CPU mesh the test suite uses, then validates everything the
observability layer promised (fast enough for CI; a tier-1 test runs
this as a subprocess):

1. **schema** — every emitted trace line parses and validates against
   the v1 schema (``tools/tracereport.validate_record``), and the run
   manifest exists with the required fields.
2. **attribution** — the injected fault's retry shows up as overhead
   seconds on the faulted op, separated from kernel seconds, and the
   fault + retry events appear in the trace.
3. **comm agreement** — the counted per-device comm words for the
   fused-pair ops match ``tools/costmodel.pair_words`` for the chosen
   strategy (the paper's measured-vs-modeled volume check).
4. **disabled overhead** — with tracing off, the per-dispatch hook cost
   (span() + metrics bookkeeping) stays in the microsecond range, far
   under the <2% bench budget.

Usage::

    python scripts/obs_smoke.py [--devices 8] [-o out.json]

Prints one JSON summary; exits nonzero if any check fails.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def run_traced_bench(tmp: str) -> dict:
    """One traced, fault-injected ALS bench run; returns paths + record."""
    from distributed_sddmm_tpu.bench.harness import benchmark_algorithm
    from distributed_sddmm_tpu.obs import trace
    from distributed_sddmm_tpu.resilience import FaultPlan, FaultSpec, fault_plan
    from distributed_sddmm_tpu.utils.coo import HostCOO

    tr = trace.enable(pathlib.Path(tmp) / "traces")
    S = HostCOO.rmat(log_m=6, edge_factor=8, seed=0)
    plan = FaultPlan([
        FaultSpec(site="execute:cgStep", kind="timeout", at=(1,)),
    ])
    with fault_plan(plan):
        record = benchmark_algorithm(
            S, "15d_fusion2", None, fused=True, R=16, c=2,
            app="als", trials=2, warmup=0,
        )
    trace.disable()
    return {
        "record": record,
        "trace_path": str(tr.path),
        "fault_events": len(plan.events),
    }


def check_schema(trace_path: str, record: dict) -> dict:
    from distributed_sddmm_tpu.obs import manifest as mani
    from distributed_sddmm_tpu.tools import tracereport

    try:
        trace = tracereport.load_trace(trace_path, strict=True)
        schema_ok = True
        schema_err = None
    except ValueError as e:
        trace = tracereport.load_trace(trace_path, strict=False)
        schema_ok, schema_err = False, str(e)

    man = tracereport.load_manifest(trace_path)
    man_ok = bool(
        man
        and man.get("schema") == mani.SCHEMA_VERSION
        and man.get("run_id")
        and "env" in man
    )
    record_linked = (
        record.get("run_id") == (trace["begin"] or {}).get("run_id")
        and record.get("trace_path") == trace_path
    )
    return {
        "name": "schema",
        "ok": bool(schema_ok and man_ok and record_linked),
        "spans": len(trace["spans"]),
        "events": len(trace["events"]),
        "schema_error": schema_err,
        "manifest_ok": man_ok,
        "record_linked": record_linked,
    }


def check_attribution(trace_path: str, record: dict, fired: int) -> dict:
    from distributed_sddmm_tpu.tools import tracereport

    trace = tracereport.load_trace(trace_path, strict=False)
    report = tracereport.aggregate(trace)
    cg = report["phases"].get("cgStep", {})
    ev = report["events"]
    metrics_cg = record.get("metrics", {}).get("cgStep", {})
    return {
        "name": "attribution",
        "ok": bool(
            fired >= 1
            and ev.get("fault_fired", 0) >= 1
            and ev.get("retry", 0) >= 1
            and cg.get("retries", 0) >= 1
            and cg.get("overhead_s", 0.0) > 0.0
            and cg.get("kernel_s", 0.0) > 0.0
            and metrics_cg.get("retries", 0) >= 1
            and metrics_cg.get("overhead_s", 0.0) > 0.0
        ),
        "cg_kernel_s": round(cg.get("kernel_s", 0.0), 4),
        "cg_overhead_s": round(cg.get("overhead_s", 0.0), 4),
        "fault_events": ev.get("fault_fired", 0),
        "retry_events": ev.get("retry", 0),
    }


def check_comm_agreement(trace_path: str) -> dict:
    from distributed_sddmm_tpu.tools import tracereport

    trace = tracereport.load_trace(trace_path, strict=False)
    report = tracereport.aggregate(trace)
    checked, ok = 0, True
    for name in ("cgStep", "fusedSpMM"):
        ph = report["phases"].get(name)
        if not ph or "model_words" not in ph:
            continue
        checked += 1
        if ph["model_words"] > 0:
            ok &= abs(ph["comm_words"] - ph["model_words"]) <= (
                1e-6 * ph["model_words"]
            )
        else:
            ok &= ph["comm_words"] == 0
    return {
        "name": "comm_agreement",
        "ok": bool(ok and checked >= 1),
        "ops_checked": checked,
    }


def check_disabled_overhead() -> dict:
    """The disabled-tracer hook cost per dispatch, measured directly."""
    from distributed_sddmm_tpu.obs import metrics, trace

    assert not trace.enabled()
    n = 20000
    om = metrics.OpMetrics()
    t0 = time.perf_counter()
    for _ in range(n):
        sp = trace.span("x")  # the per-dispatch disabled-path hooks
        om.record("x", 1e-6, comm_words=1.0, flops=1.0)
    per_call_us = (time.perf_counter() - t0) / n * 1e6
    return {
        "name": "disabled_overhead",
        # Generous CI bound: the real budget is <2% of a bench whose
        # dispatches cost milliseconds; 50us/call would still pass that.
        "ok": bool(sp is trace.NOOP_SPAN and per_call_us < 50.0),
        "per_call_us": round(per_call_us, 3),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("-o", "--output-file", default=None)
    args = ap.parse_args(argv)

    from distributed_sddmm_tpu.utils.platform import force_cpu_platform

    force_cpu_platform(n_devices=args.devices, replace=True)

    checks = []
    with tempfile.TemporaryDirectory() as tmp:
        try:
            run = run_traced_bench(tmp)
            checks.append(check_schema(run["trace_path"], run["record"]))
            checks.append(check_attribution(
                run["trace_path"], run["record"], run["fault_events"]
            ))
            checks.append(check_comm_agreement(run["trace_path"]))
        except Exception as e:  # noqa: BLE001 — a smoke run reports
            checks.append({
                "name": "traced_bench", "ok": False,
                "error": f"{type(e).__name__}: {e}",
            })
        try:
            checks.append(check_disabled_overhead())
        except Exception as e:  # noqa: BLE001
            checks.append({
                "name": "disabled_overhead", "ok": False,
                "error": f"{type(e).__name__}: {e}",
            })

    ok = all(c["ok"] for c in checks)
    out = {"ok": ok, "devices": args.devices, "checks": checks}
    blob = json.dumps(out, indent=1)
    print(blob)
    if args.output_file:
        with open(args.output_file, "w") as f:
            f.write(blob + "\n")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
