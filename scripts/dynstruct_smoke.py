"""CPU-mesh dynamic-structure smoke: the dynstruct/ layer end to end.

Four checks on the same virtual 8-device CPU mesh the test suite uses
(fast enough for CI; a tier-1 test runs this as a subprocess):

1. **growth_storm** — a dynstruct-built strategy absorbs a storm of
   ``append_rows`` growth steps through :func:`dynstruct.rebind`: every
   step fits its capacity rung, the compiled programs keep serving
   (ZERO live compiles after the warmup trace — the ``live_compiles``
   GLOBAL currency), and the final SDDMM output is bit-identical to a
   freshly-traced cold rebuild at the same capacity.
2. **mask_churn_storm** — a ``dynamic=True`` attention engine serves a
   storm of per-request ``window:<w>`` / ``topk:<k>`` mask changes with
   zero post-warmup cache misses, every reply matching the float64
   oracle and bit-identical to a freshly-traced engine of the same
   capacity.
3. **context_rebind** — ``engine.rebind_structure`` binds a grown
   context in place (fit: zero new compiles), then a rung-outgrowing
   one (spill: ladder re-warms, replies stay correct) — and the
   rebind/spill/retrace counters tell the story.
4. **als_ingest_rebind** — the online-learning loop: a serving ALS
   fold-in engine ingests live traffic (``append_rows`` on S_live),
   rebinds the grown pattern into the model's training strategy, and
   keeps serving with zero new compiles.

Usage::

    python scripts/dynstruct_smoke.py [-o out.json]

Prints one JSON summary; exits nonzero if any check fails.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def _live_compiles() -> float:
    from distributed_sddmm_tpu.obs import metrics as obs_metrics

    return obs_metrics.GLOBAL.get("live_compiles")


def _sddmm_out(alg):
    """One SDDMM through the strategy's compiled program; gathered host
    values in canonical nonzero order (the bit-identity currency)."""
    from distributed_sddmm_tpu.parallel.base import KernelMode, MatMode

    A = alg.dummy_initialize(MatMode.A)
    B = alg.dummy_initialize(MatMode.B)
    A_s, B_s = alg.initial_shift(A, B, KernelMode.SDDMM_A)
    mid = alg.sddmm_a(A_s, B_s, alg.like_s_values(1.0))
    return alg.gather_s_values(mid)


def check_growth_storm(rounds: int = 6) -> dict:
    import numpy as np

    from distributed_sddmm_tpu import dynstruct
    from distributed_sddmm_tpu.utils.coo import HostCOO

    S = HostCOO.erdos_renyi(100, 64, 4, seed=0, values="normal")
    alg = dynstruct.build("15d_fusion2", S, 16, 2, headroom=4.0)
    _sddmm_out(alg)  # warmup trace
    live0 = _live_compiles()
    rng = np.random.default_rng(1)
    fits = 0
    for _ in range(rounds):
        n = int(rng.integers(1, 4))
        cols = rng.choice(S.N, size=n, replace=False).astype(np.int64)
        S.append_rows([cols], [rng.standard_normal(n)], mode="repair")
        update = dynstruct.rebind(alg, S)
        fits += bool(update.fit)
        _sddmm_out(alg)
    live_delta = _live_compiles() - live0
    # Bit-identity vs a COLD rebuild at the same capacity: a fresh
    # build + fresh trace over the grown pattern must reproduce the
    # rebound program's output exactly.
    cold = dynstruct.build("15d_fusion2", S, 16, 2, headroom=4.0)
    bit_identical = bool(np.array_equal(_sddmm_out(alg), _sddmm_out(cold)))
    return {
        "name": "growth_storm",
        "ok": bool(fits == rounds and live_delta == 0 and bit_identical),
        "rounds": rounds,
        "fits": fits,
        "live_compiles_after_warmup": live_delta,
        "bit_identical_vs_cold": bit_identical,
    }


def _attention_engine(ctx, window: int = 4, dynamic: bool = True):
    from distributed_sddmm_tpu.serve import ServingEngine
    from distributed_sddmm_tpu.serve.workloads import AttentionTokenScore

    workload = AttentionTokenScore(
        ctx, window=window, token_buckets=(4, 8), dynamic=dynamic
    )
    engine = ServingEngine(
        workload, max_batch=4, max_depth=16, max_wait_ms=2.0
    )
    engine.warmup()
    return workload, engine


def _churn_payloads(rng, n_ctx: int, window: int, count: int) -> list:
    import numpy as np

    out = []
    for i in range(count):
        n = int(rng.integers(1, 5))
        p = {"tokens": rng.choice(n_ctx, size=n, replace=False).astype(
            np.int64
        )}
        if i % 3 == 1:
            p["mask"] = f"window:{int(rng.integers(0, window + 1))}"
        elif i % 3 == 2:
            p["mask"] = f"topk:{int(rng.integers(1, 2 * window + 2))}"
        out.append(p)
    return out


def check_mask_churn_storm() -> dict:
    import numpy as np

    rng = np.random.default_rng(2)
    ctx = rng.standard_normal((200, 16)).astype(np.float32)
    workload, engine = _attention_engine(ctx)
    misses0 = engine.stats()["cache_misses"]
    live0 = _live_compiles()
    payloads = _churn_payloads(rng, workload.n_ctx, workload.window, 30)
    replies = []
    for i in range(0, len(payloads), 3):
        replies.extend(engine.execute_now(payloads[i : i + 3]))
    oracle_ok = all(
        workload.check_reply(p, r) for p, r in zip(payloads, replies)
    )
    stats = engine.stats()
    # Freshly-traced twin at the same capacity: same context, fresh
    # programs — replies must agree bit-for-bit.
    _, engine2 = _attention_engine(ctx)
    replies2 = []
    for i in range(0, len(payloads), 3):
        replies2.extend(engine2.execute_now(payloads[i : i + 3]))
    bit_identical = all(
        np.array_equal(a["scores"], b["scores"])
        for a, b in zip(replies, replies2)
    )
    return {
        "name": "mask_churn_storm",
        "ok": bool(
            oracle_ok and bit_identical
            and stats["cache_misses"] == misses0
            and _live_compiles() - live0 == 0
        ),
        "requests": len(payloads),
        "cache_misses_after_warmup": stats["cache_misses"] - misses0,
        "oracle_ok": oracle_ok,
        "bit_identical_vs_fresh": bit_identical,
    }


def check_context_rebind() -> dict:
    import numpy as np

    from distributed_sddmm_tpu.obs import metrics as obs_metrics

    rng = np.random.default_rng(3)
    ctx = rng.standard_normal((200, 16)).astype(np.float32)
    workload, engine = _attention_engine(ctx)
    cap0 = workload.ctx_cap
    misses0 = engine.stats()["cache_misses"]
    # Fit: grow within the rung; the compiled cells keep serving.
    grown = np.concatenate(
        [ctx, rng.standard_normal((40, 16)).astype(np.float32)]
    )
    rep_fit = engine.rebind_structure(grown)
    p = {"tokens": np.array([205, 239], dtype=np.int64)}
    reply = engine.execute_now([p])[0]
    fit_ok = (
        rep_fit["fit"]
        and workload.ctx_cap == cap0
        and engine.stats()["cache_misses"] == misses0
        and workload.check_reply(p, reply)
    )
    # Spill: outgrow the rung; the engine re-warms its ladder and the
    # spill is the counted retrace.
    huge = np.concatenate(
        [grown, rng.standard_normal((300, 16)).astype(np.float32)]
    )
    rep_spill = engine.rebind_structure(huge)
    p2 = {"tokens": np.array([500, 539], dtype=np.int64), "mask": "topk:3"}
    reply2 = engine.execute_now([p2])[0]
    spill_ok = (
        not rep_spill["fit"]
        and workload.ctx_cap > cap0
        and engine.stats()["cache_misses"] > misses0
        and workload.check_reply(p2, reply2)
    )
    snap = obs_metrics.GLOBAL.snapshot()
    counters_ok = (
        snap.get("dynstruct_rebinds", 0) >= 1
        and snap.get("dynstruct_bucket_spills", 0) >= 1
        and snap.get("structure_retraces", 0) >= 1
    )
    return {
        "name": "context_rebind",
        "ok": bool(fit_ok and spill_ok and counters_ok),
        "fit": rep_fit,
        "spill": rep_spill,
        "counters": {
            k: snap.get(k, 0)
            for k in ("dynstruct_rebinds", "dynstruct_bucket_spills",
                      "structure_retraces")
        },
    }


def check_als_ingest_rebind() -> dict:
    import numpy as np

    from distributed_sddmm_tpu import dynstruct
    from distributed_sddmm_tpu.models.als import DistributedALS
    from distributed_sddmm_tpu.serve import ALSFoldInTopK, ServingEngine
    from distributed_sddmm_tpu.utils.coo import HostCOO

    S = HostCOO.erdos_renyi(64, 48, 6, seed=4, values="normal")
    alg = dynstruct.build("15d_fusion2", S, 8, 1, headroom=4.0)
    model = DistributedALS(alg, S_host=S)
    model.run_cg(2, cg_iters=4)
    workload = ALSFoldInTopK(model, k=5, item_buckets=(4, 8))
    engine = ServingEngine(
        workload, max_batch=4, max_depth=16, max_wait_ms=2.0
    )
    engine.warmup()
    rng = np.random.default_rng(5)
    payloads = [workload.sample_payload(rng) for _ in range(6)]
    misses0 = engine.stats()["cache_misses"]
    live0 = _live_compiles()
    nnz0 = S.nnz
    replies = engine.execute_now(payloads)
    workload.ingest(payloads)
    report = engine.rebind_structure()
    replies_after = engine.execute_now(payloads)
    oracle_ok = all(
        workload.check_reply(p, r)
        for p, r in zip(payloads, replies_after)
    )
    bit_identical = all(
        np.array_equal(a["items"], b["items"])
        and np.array_equal(a["scores"], b["scores"])
        for a, b in zip(replies, replies_after)
    )
    stats = engine.stats()
    return {
        "name": "als_ingest_rebind",
        "ok": bool(
            report["fit"]
            and S.nnz > nnz0
            and oracle_ok
            and bit_identical
            and stats["cache_misses"] == misses0
            and _live_compiles() - live0 == 0
            and stats["structure_rebinds"] == 1
        ),
        "ingested_nnz": S.nnz - nnz0,
        "rebind": report,
        "cache_misses_after_warmup": stats["cache_misses"] - misses0,
        "oracle_ok": oracle_ok,
        "bit_identical_across_rebind": bit_identical,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-o", "--output-file", default=None)
    args = ap.parse_args(argv)

    from distributed_sddmm_tpu.utils.platform import force_cpu_platform

    force_cpu_platform(n_devices=8, replace=True)

    t0 = time.perf_counter()
    checks = [
        check_growth_storm(),
        check_mask_churn_storm(),
        check_context_rebind(),
        check_als_ingest_rebind(),
    ]
    report = {
        "ok": all(c["ok"] for c in checks),
        "elapsed_s": round(time.perf_counter() - t0, 2),
        "checks": checks,
    }
    text = json.dumps(report, indent=1)
    print(text)
    if args.output_file:
        pathlib.Path(args.output_file).write_text(text)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
