"""CPU-mesh autotune smoke run: cost-model-only plan selection.

Exercises the whole selection pipeline — fingerprint, candidate
enumeration, HBM guards, cost-model ranking, cache store/recall — with
zero measured trials, on the same virtual 8-device CPU mesh the test
suite uses. Fast enough for CI (a tier-1 test runs it as a subprocess);
useful standalone as a health check that every probe problem still gets
a constructible plan, including the heavy corner (logM=16, nnz/row=128,
R=512) that must route onto the chunked XLA kernel rather than a >HBM
gather.

Usage::

    python scripts/autotune_smoke.py [--devices 8] [-o out.json]

Prints one JSON summary; exits nonzero if any probe problem fails to
produce a plan or the heavy corner is not chunk-routed.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


# Probe problems spanning the five algorithm configs' home regimes
# (paper heatmap axes: size, density, R). Shapes are scaled down from the
# reference grid so the smoke run needs no big host allocations — the
# selection path is size-independent; only the chosen plans differ.
PROBES = [
    {"name": "headline", "M": 1 << 12, "npr": 32, "R": 128},
    {"name": "dense_rows", "M": 1 << 10, "npr": 128, "R": 64},
    {"name": "sparse_highR", "M": 1 << 12, "npr": 8, "R": 512},
    {"name": "small_lowR", "M": 1 << 10, "npr": 8, "R": 16},
    {"name": "square_midR", "M": 1 << 11, "npr": 32, "R": 256},
    # The reference grid's OOM corner at full size, probed single-device
    # (the kernel-sweep context where its nnz*R gather ~ 17 GB first blew
    # HBM): must emerge chunk-routed, never crash or prune away.
    {"name": "heavy_corner", "M": 1 << 16, "npr": 128, "R": 512, "p": 1},
]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("-o", "--output-file", default=None)
    args = ap.parse_args(argv)

    from distributed_sddmm_tpu.utils.platform import force_cpu_platform

    force_cpu_platform(n_devices=args.devices, replace=True)

    from distributed_sddmm_tpu.autotune import PlanCache, Problem, get_plan

    ok = True
    results = []
    with tempfile.TemporaryDirectory() as tmp:
        cache = PlanCache(tmp)
        import jax

        for probe in PROBES:
            prob = Problem(
                M=probe["M"], N=probe["M"], nnz=probe["M"] * probe["npr"],
                R=probe["R"],
            )
            devices = jax.devices()[: probe["p"]] if "p" in probe else None
            t0 = time.perf_counter()
            try:
                plan = get_plan(prob, devices, mode="model", cache=cache)
            except Exception as e:  # noqa: BLE001 — a smoke run reports, not raises
                results.append({"probe": probe, "error": f"{type(e).__name__}: {e}"})
                ok = False
                continue
            cold_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            get_plan(prob, devices, mode="model", cache=cache)  # warm: cache hit
            warm_s = time.perf_counter() - t0
            rec = {
                "probe": probe,
                "plan": plan.to_dict(),
                "cold_s": round(cold_s, 4),
                "warm_s": round(warm_s, 4),
            }
            if probe["name"] == "heavy_corner" and plan.kernel == "xla":
                rec["chunk_routed"] = plan.gather_budget is not None
                ok &= rec["chunk_routed"]
            results.append(rec)

    out = {"ok": ok, "devices": args.devices, "mode": "model", "probes": results}
    blob = json.dumps(out, indent=1)
    print(blob)
    if args.output_file:
        with open(args.output_file, "w") as f:
            f.write(blob + "\n")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
