"""CPU-mesh serving smoke: the online layer end to end.

Four checks on the same virtual 8-device CPU mesh the test suite uses
(fast enough for CI; a tier-1 test runs this as a subprocess):

1. **determinism** — one warm ALS fold-in engine; replies are
   bit-identical across batch compositions and match the float64
   oracle; the whole bucket ladder is compiled at warmup and live
   requests only ever hit the cache.
2. **backpressure** — with no runner draining, submissions beyond
   ``max_depth`` shed with a retry-after hint and the queue stays
   bounded.
3. **faulted load** — an open-loop Poisson run under an injected
   ``delay,nan`` storm: every request is answered or shed, zero
   incorrect replies, the engine never dies.
4. **slo** — the same summary judged against a tight SLO (must
   violate) and a loose one (must pass): the gate axis works.

Usage::

    python scripts/serve_smoke.py [-o out.json]

Prints one JSON summary; exits nonzero if any check fails.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def _build_serving(seed: int = 0):
    import numpy as np

    from distributed_sddmm_tpu.models.als import DistributedALS
    from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
    from distributed_sddmm_tpu.serve import ALSFoldInTopK, ServingEngine
    from distributed_sddmm_tpu.utils.coo import HostCOO

    S = HostCOO.erdos_renyi(64, 48, 6, seed=seed, values="normal")
    alg = DenseShift15D(S, R=8, c=1, fusion_approach=2)
    model = DistributedALS(alg, S_host=S)
    model.run_cg(2, cg_iters=4)
    workload = ALSFoldInTopK(model, k=5, item_buckets=(4, 8))
    engine = ServingEngine(
        workload, max_batch=4, max_depth=16, max_wait_ms=4.0
    )
    rng = np.random.default_rng(seed + 1)
    payloads = [workload.sample_payload(rng) for _ in range(6)]
    return workload, engine, payloads


def check_determinism(workload, engine, payloads) -> dict:
    import numpy as np

    warmed = engine.warmup()
    stats0 = engine.stats()
    batched = engine.execute_now(payloads)
    solos = [engine.execute_now([p])[0] for p in payloads]
    bit_identical = all(
        np.array_equal(a["items"], b["items"])
        and np.array_equal(a["scores"], b["scores"])
        for a, b in zip(batched, solos)
    )
    oracle_ok = all(
        workload.check_reply(p, r) for p, r in zip(payloads, batched)
    )
    stats = engine.stats()
    return {
        "name": "determinism",
        "ok": bool(
            bit_identical and oracle_ok
            and warmed == stats0["cache_misses"]
            and stats["cache_misses"] == stats0["cache_misses"]
        ),
        "bit_identical": bit_identical,
        "oracle_ok": oracle_ok,
        "programs": stats["programs"],
        "live_compiles": stats["cache_misses"] - stats0["cache_misses"],
    }


def check_backpressure(workload) -> dict:
    import numpy as np

    from distributed_sddmm_tpu.serve import ServingEngine, ShedError

    engine = ServingEngine(
        workload, max_batch=2, max_depth=4, max_wait_ms=1.0
    )
    rng = np.random.default_rng(9)
    shed = 0
    retry_after_sane = True
    for _ in range(10):
        try:
            engine.submit(workload.sample_payload(rng))
        except ShedError as e:
            shed += 1
            retry_after_sane &= e.retry_after_s >= 0.0
    depth = engine.queue.depth()
    engine.queue.close()
    return {
        "name": "backpressure",
        "ok": bool(shed == 6 and depth == 4 and retry_after_sane),
        "shed": shed,
        "depth": depth,
    }


def check_faulted_load(workload) -> dict:
    from distributed_sddmm_tpu.resilience import FaultPlan, fault_plan
    from distributed_sddmm_tpu.serve import ServingEngine, run_load

    engine = ServingEngine(
        workload, max_batch=4, max_depth=8, max_wait_ms=2.0
    )
    plan = FaultPlan.from_spec("delay,nan")
    engine.start(warmup=False)
    try:
        with fault_plan(plan):
            summary = run_load(
                engine, duration_s=1.5, rate_hz=40, seed=3, oracle_every=3
            )
    finally:
        engine.stop()
    accounted = (
        summary["completed"] + summary["shed_count"] == summary["requests"]
    )
    return {
        "name": "faulted_load",
        "ok": bool(
            accounted
            and summary["errors"] == 0
            and summary["oracle_failures"] == 0
            and len(plan.events) > 0
        ),
        "requests": summary["requests"],
        "completed": summary["completed"],
        "shed": summary["shed_count"],
        "degraded": summary["degraded_count"],
        "faults_fired": len(plan.events),
        "oracle_failures": summary["oracle_failures"],
        "p99_ms": summary["latency_ms"].get("p99"),
    }


def check_slo(workload) -> dict:
    from distributed_sddmm_tpu.serve import ServingEngine, SLOSpec, run_load

    engine = ServingEngine(
        workload, max_batch=4, max_depth=16, max_wait_ms=2.0
    )
    engine.start(warmup=False)
    try:
        summary = run_load(
            engine, duration_s=1.0, rate_hz=30, seed=4, oracle_every=0,
            slo=SLOSpec.parse("p99_ms=0.001"),  # impossibly tight
        )
    finally:
        engine.stop()
    tight_violates = bool(summary["slo_violations"])
    loose_passes = not SLOSpec.parse("p99_ms=60000,err_rate=0.5").check(
        summary
    )
    return {
        "name": "slo",
        "ok": bool(tight_violates and loose_passes and summary["completed"]),
        "tight_violations": summary["slo_violations"],
        "completed": summary["completed"],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-o", "--output-file", default=None)
    args = ap.parse_args(argv)

    from distributed_sddmm_tpu.utils.platform import force_cpu_platform

    force_cpu_platform(n_devices=8, replace=True)

    t0 = time.perf_counter()
    workload, engine, payloads = _build_serving()
    checks = [check_determinism(workload, engine, payloads)]
    # The remaining checks build their own engines over the same warm
    # workload (programs recompile per engine; the matrices are tiny).
    checks.append(check_backpressure(workload))
    checks.append(check_faulted_load(workload))
    checks.append(check_slo(workload))

    report = {
        "ok": all(c["ok"] for c in checks),
        "elapsed_s": round(time.perf_counter() - t0, 2),
        "checks": checks,
    }
    text = json.dumps(report, indent=1)
    print(text)
    if args.output_file:
        pathlib.Path(args.output_file).write_text(text)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
