"""Attention smoke: masks, fused pair, counted HBM cut, serving.

One process, four sections, JSON report (the tier-1 test
``tests/test_attention_smoke.py`` asserts on it):

* **masks** — the three structured families build over one token count
  with sane degree profiles; the spec grammar round-trips.
* **oracle** — the fused SDDMM → masked-softmax → SpMM pair matches the
  float64 oracle on every mask family (fully-masked rows come back
  exactly zero, never NaN), on the XLA path AND the banked Pallas
  interpreter path, and the attention weights are row-stochastic.
* **fusion** — fused vs the three-program unfused baseline agree
  BIT-FOR-BIT on integer-exact data, the fused run dispatches ONE
  program, and counted HBM traffic is strictly below unfused on the
  headline configs (sliding-window and BigBird, R in {128, 1024}).
* **serve** — the token-scoring endpoint built on a fused-attention
  warm context replies bit-identically across batch composition and
  matches its float64 oracle.

Exit contract: 0 clean, 2 on any failed check.

Usage::

    python scripts/attention_smoke.py [-o out.json]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def run() -> dict:
    from distributed_sddmm_tpu.utils.platform import force_cpu_platform

    force_cpu_platform(n_devices=8, replace=True)

    import numpy as np

    from distributed_sddmm_tpu import codegen, masks
    from distributed_sddmm_tpu.autotune.fingerprint import Problem
    from distributed_sddmm_tpu.bench.harness import _attention_hbm_bytes
    from distributed_sddmm_tpu.parallel.dense_shift_15d import DenseShift15D
    from distributed_sddmm_tpu.serve import build_attention_engine
    from distributed_sddmm_tpu.utils import oracle
    from distributed_sddmm_tpu.utils.coo import HostCOO

    report: dict = {}
    rng = np.random.default_rng(0)

    # ------------------------------------------------------------------ #
    # 1. Masks
    # ------------------------------------------------------------------ #
    n = 192
    graph_src = HostCOO.rmat(log_m=8, edge_factor=4, seed=0)
    fams = {
        "window:5": masks.from_spec("window:5", n),
        "bigbird:w=3,g=2,r=2": masks.from_spec("bigbird:w=3,g=2,r=2", n),
        "graph": masks.from_spec("graph", n, graph=graph_src),
    }
    report["masks"] = {
        spec: {
            "n": S.M, "nnz": S.nnz,
            "max_deg": int(np.bincount(S.rows, minlength=S.M).max()),
        }
        for spec, S in fams.items()
    }
    assert fams["window:5"].nnz == masks.sliding_window(n, 5).nnz

    # ------------------------------------------------------------------ #
    # 2. Oracle across families (+ fully masked rows), XLA and banked
    # ------------------------------------------------------------------ #
    R = 16
    oracle_report = {}
    for spec, S0 in fams.items():
        vals = np.ones(S0.nnz)
        vals[rng.random(S0.nnz) < 0.1] = 0.0
        vals[S0.rows == 2] = 0.0  # fully masked row
        S = S0.with_values(vals)
        A = rng.standard_normal((S.M, R))
        B = rng.standard_normal((S.N, R))
        want_out, want_probs = oracle.fused_attention_a(S, A, B)
        errs = {}
        for kname, kern in (
            ("xla", None),
            ("banked", codegen.BankedPallasKernel(
                codegen.select_variant(Problem.from_coo(S, R=R)),
                precision="f32", interpret=True,
            )),
        ):
            alg = DenseShift15D(S, R=R, c=1, fusion_approach=2, kernel=kern)
            out, probs = alg.fused_attention(
                alg.put_a(A.astype(np.float32)),
                alg.put_b(B.astype(np.float32)),
                alg.scatter_s_values(vals.astype(np.float32)),
            )
            out_h = alg.host_a(out)
            p_h = alg.gather_s_values(probs)
            errs[kname] = {
                "out": float(np.max(np.abs(out_h - want_out))),
                "probs": float(np.max(np.abs(p_h - want_probs))),
            }
            assert errs[kname]["out"] < 1e-4, (spec, kname, errs)
            assert errs[kname]["probs"] < 1e-5, (spec, kname, errs)
            assert np.all(out_h[2] == 0.0), (spec, kname)  # dead row
            assert np.isfinite(out_h).all(), (spec, kname)
            sums = np.zeros(S.M)
            np.add.at(sums, S.rows, p_h)
            live = np.zeros(S.M, dtype=bool)
            live[S.rows[vals != 0]] = True
            assert np.allclose(sums[live], 1.0, atol=1e-5), (spec, kname)
        oracle_report[spec] = errs
    report["oracle"] = oracle_report

    # ------------------------------------------------------------------ #
    # 3. Fusion: bit agreement, one program, counted HBM cut
    # ------------------------------------------------------------------ #
    S0 = masks.bigbird(160, 3, 2, 2)
    vals = np.ones(S0.nnz)
    vals[rng.random(S0.nnz) < 0.1] = 0.0
    S = S0.with_values(vals)
    alg = DenseShift15D(S, R=8, c=1, fusion_approach=2)
    A = alg.put_a(rng.integers(-3, 4, (S.M, 8)).astype(np.float32))
    B = alg.put_b(rng.integers(-3, 4, (S.N, 8)).astype(np.float32))
    sv = alg.scatter_s_values(vals.astype(np.float32))
    out_f, p_f = alg.fused_attention(A, B, sv)
    calls = alg.metrics.calls_view()
    out_u, p_u = alg.attention_unfused(A, B, sv)
    bit_identical = bool(
        np.array_equal(np.asarray(out_f), np.asarray(out_u))
        and np.array_equal(np.asarray(p_f), np.asarray(p_u))
    )
    hbm = {}
    for spec in ("window:8", "bigbird:w=4,g=2,r=2"):
        for R_h in (128, 1024):
            Sm = masks.from_spec(spec, 256)
            alg_h = DenseShift15D(Sm, R=R_h, c=1, fusion_approach=2)
            h = _attention_hbm_bytes(alg_h, alg_h.like_s_values(1.0))
            hbm[f"{spec}@R{R_h}"] = h
            assert h["fused_bytes"] < h["unfused_bytes"], (spec, R_h, h)
    report["fusion"] = {
        "bit_identical": bit_identical,
        "fused_dispatches": calls.get("fusedAttn"),
        "hbm": hbm,
    }
    assert bit_identical, report["fusion"]
    assert calls.get("fusedAttn") == 1, calls

    # ------------------------------------------------------------------ #
    # 4. Serving: batch-composition bit identity + oracle
    # ------------------------------------------------------------------ #
    eng = build_attention_engine(
        masks.sliding_window(128, 6), R=8, window=4,
        max_batch=8, max_depth=16, token_buckets=(2, 4),
    )
    eng.warmup()
    wl = eng.workload
    payloads = [wl.sample_payload(rng) for _ in range(5)]
    base = eng.execute_now(payloads)

    def eq(a, b):
        return set(a) == set(b) and all(
            np.array_equal(np.asarray(a[k]), np.asarray(b[k])) for k in a
        )

    order_ok = all(
        eq(eng.execute_now([payloads[i] for i in perm])[where], base[i])
        for perm in ([4, 2, 0, 3, 1],)
        for where, i in enumerate(perm)
    )
    solo_ok = all(
        eq(eng.execute_now([p])[0], base[i])
        for i, p in enumerate(payloads)
    )
    oracle_ok = all(
        wl.check_reply(p, base[i]) and wl.check_reply(p, wl.serial(p))
        for i, p in enumerate(payloads)
    )
    report["serve"] = {
        "arrival_order_bit_identical": order_ok,
        "padding_bit_identical": solo_ok,
        "oracle_ok": oracle_ok,
        "kernel_variant": wl.kernel_variant,
        "window": wl.window,
    }
    assert order_ok and solo_ok and oracle_ok, report["serve"]
    return report


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-o", "--output-file", default=None)
    args = ap.parse_args()
    try:
        report = run()
    except AssertionError as e:
        print(json.dumps({"failed": str(e)[:2000]}))  # cli-output
        return 2
    out = json.dumps(report, indent=2, default=str)
    print(out)  # cli-output
    if args.output_file:
        pathlib.Path(args.output_file).write_text(out)  # non-atomic-ok: smoke artifact
    return 0


if __name__ == "__main__":
    sys.exit(main())
