"""Lint smoke: the analyzer's exit contract, end to end, per checker.

Three legs (fast, jax-free; a tier-1 test runs this as a subprocess):

1. **clean tree** — ``bench lint`` over this checkout with the
   committed baseline exits 0: every discipline holds or is tagged/
   baselined. This is the CI gate the committed tree must keep.
2. **seeded violations** — a throwaway tree seeded with ONE violation
   per checker (mirroring the package layout so path-scoped checkers
   fire) makes the analyzer exit 2, and each checker id appears among
   the findings: the visitors cannot silently rot. A tagged variant of
   each seed is also planted and must be suppressed — the one shared
   tag scanner works for every checker's vocabulary.
3. **usage errors** — an unknown ``--checker`` id and an unreadable
   ``--baseline`` both exit 3, distinct from a lint verdict.

Usage::

    python scripts/lint_smoke.py [-o out.json]

Prints one JSON summary; exits nonzero if any check fails.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import tempfile

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

PKG = "distributed_sddmm_tpu"

#: One violating snippet per checker, placed so its path scope matches,
#: with a sibling tagged line that must be suppressed by the shared
#: scanner. Format: (relative path, source).
SEEDS = {
    "bare-print": (f"{PKG}/models/seeded.py", (
        "def f():\n"
        "    print('leak')\n"
        "    print('deliberate')  # cli-output\n"
    )),
    "monotonic-clock": (f"{PKG}/serve/seeded.py", (
        "import time\n"
        "def f():\n"
        "    t = time.perf_counter()\n"
        "    u = time.time()  # wall-clock-ok\n"
        "    return t, u\n"
    )),
    "export-completeness": (f"{PKG}/obs/seeded.py", (
        "from distributed_sddmm_tpu.obs.metrics import GLOBAL\n"
        "def f():\n"
        "    GLOBAL.add('totally_bogus_counter')\n"
        "    GLOBAL.add('also_bogus')  # not-exported\n"
    )),
    "atomic-write": (f"{PKG}/obs/seeded2.py", (
        "def f(path, doc):\n"
        "    with open(path, 'w') as fh:\n"
        "        fh.write(doc)\n"
        "    # non-atomic-ok: seeded stream\n"
        "    with open(path, 'a') as fh:\n"
        "        fh.write(doc)\n"
    )),
    "env-knob": (f"{PKG}/utils/seeded.py", (
        "import os\n"
        "def f():\n"
        "    a = os.environ.get('DSDDMM_SEEDED_BOGUS_KNOB')\n"
        "    b = os.environ.get('DSDDMM_OTHER_BOGUS')  # env-ok\n"
        "    return a, b\n"
    )),
    "lock-discipline": (f"{PKG}/serve/seeded2.py", (
        "import threading\n"
        "_lock = threading.Lock()\n"
        "_registry = {}\n"
        "def unguarded(k, v):\n"
        "    _registry[k] = v\n"
        "def guarded(k, v):\n"
        "    with _lock:\n"
        "        _registry[k] = v\n"
        "def annotated(k, v):\n"
        "    _registry[k] = v  # unlocked-ok\n"
    )),
    "key-grammar": (f"{PKG}/serve/seeded3.py", (
        "def f(fp, op, sig, backend, code):\n"
        "    bad = f'plan:{fp}:{op}:{sig}:{backend}:{code}'\n"
        "    ok = f'serve:{op}:b1:i2:r{sig}:{backend}'  # key-grammar-ok\n"
        "    return bad, ok\n"
    )),
    "trace-purity": (f"{PKG}/ops/seeded.py", (
        "import time\n"
        "import jax\n"
        "@jax.jit\n"
        "def traced(x):\n"
        "    t = time.time()\n"
        "    u = time.perf_counter()  # trace-impure-ok\n"
        "    return x + t + u\n"
    )),
}


def run_lint(argv, cwd=None):
    """The analyzer CLI in-process (no jax import needed)."""
    from distributed_sddmm_tpu.analysis import cli as analysis_cli

    import contextlib
    import io

    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        code = analysis_cli.main(["lint", *argv])
    return code, out.getvalue()


def check_clean_tree() -> dict:
    code, out = run_lint(["--json"])
    doc = json.loads(out)
    return {
        "ok": code == 0 and doc["new"] == 0,
        "exit": code,
        "new": doc["new"],
        "tagged": doc["tagged"],
    }


def check_seeded(tmp: pathlib.Path) -> dict:
    root = tmp / "seeded_tree"
    for rel, src in SEEDS.values():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    code, out = run_lint(["--root", str(root), "--json"])
    doc = json.loads(out)
    fired = {f["checker"] for f in doc["findings"] if f["state"] == "new"}
    suppressed = {f["checker"] for f in doc["findings"]
                  if f["state"] == "tagged"}
    missing = sorted(set(SEEDS) - fired)
    unsuppressed = sorted(set(SEEDS) - suppressed)
    return {
        "ok": code == 2 and not missing and not unsuppressed,
        "exit": code,
        "fired": sorted(fired),
        "missing_checkers": missing,
        "tag_scanner_missed": unsuppressed,
    }


def check_usage_errors(tmp: pathlib.Path) -> dict:
    bad_checker, _ = run_lint(["--checker", "no-such-checker"])
    garbled = tmp / "garbled_baseline.json"
    garbled.write_text("{not json")
    bad_baseline, _ = run_lint(["--baseline", str(garbled)])
    return {
        "ok": bad_checker == 3 and bad_baseline == 3,
        "unknown_checker_exit": bad_checker,
        "unreadable_baseline_exit": bad_baseline,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("-o", "--output-file", default=None)
    args = ap.parse_args(argv)

    with tempfile.TemporaryDirectory(prefix="lint_smoke_") as tmp:
        tmp = pathlib.Path(tmp)
        summary = {
            "clean_tree": check_clean_tree(),
            "seeded_violations": check_seeded(tmp),
            "usage_errors": check_usage_errors(tmp),
        }
    summary["ok"] = all(leg["ok"] for leg in summary.values())
    text = json.dumps(summary, indent=1)
    print(text)
    if args.output_file:
        from distributed_sddmm_tpu.utils.atomic import atomic_write_text

        atomic_write_text(args.output_file, text)
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
