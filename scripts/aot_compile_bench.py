"""Offline AOT compiler for the headline benchmark's chained program.

Companion to bench.py's BENCH_AOT_DIR mode: construct the identical
headline strategy (bench.build_headline, same env knobs), retarget its
mesh at one v5e topology device (the run_pallas.py pattern), and
AOT-compile + serialize `bench.make_headline_chain` for both trip counts —
locally, in seconds, while the on-device route costs minutes of remote
Mosaic compile per distinct program.

CPU-pinned; invoked by bench.py's orchestrator when AOT_LOAD.json records
that re-homed loads work on this backend.

Usage: python scripts/aot_compile_bench.py OUT_DIR
Env: BENCH_LOG_M/BENCH_NNZ_PER_ROW/BENCH_R/BENCH_TRIALS + DSDDMM_* knobs,
exactly as bench.py's worker reads them.
"""

from __future__ import annotations

import importlib.util
import json
import os
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

import jax

jax.config.update("jax_platforms", "cpu")

from jax.experimental import topologies

TOPOLOGY = "v5e:2x4"


def main() -> int:
    out_dir = pathlib.Path(sys.argv[1])
    trials = int(os.environ.get("BENCH_TRIALS", "5"))

    spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    from distributed_sddmm_tpu.ops.kernels import XlaKernel
    from distributed_sddmm_tpu.ops.pallas_kernels import PallasKernel
    from distributed_sddmm_tpu.parallel.mesh import make_grid

    # Compile exactly what the on-device worker would run: get_kernel
    # ("auto") resolves to the bf16 Mosaic kernel on TPU; the Mosaic-outage
    # rescue rung exports BENCH_KERNEL=xla and gets the flat XLA program.
    if os.environ.get("BENCH_KERNEL", "auto") == "xla":
        kernel = XlaKernel()
    else:
        kernel = PallasKernel(precision="bf16", interpret=False)
    t0 = time.monotonic()
    alg, _prog, A, B, targs = bench.build_headline(
        kernel, devices=jax.devices("cpu")[:1])
    build_s = round(time.monotonic() - t0, 1)

    topo = topologies.get_topology_desc(platform="tpu", topology_name=TOPOLOGY)
    g = alg.grid
    alg.grid = make_grid(g.nr, g.nc, g.nh, adjacency=g.adjacency,
                         devices=[topo.devices[0]])
    alg._programs.clear()
    prog = alg._program("fused", use_st=False)
    mesh = alg.grid.mesh

    def sds_like(x):
        sharding = jax.sharding.NamedSharding(mesh, x.sharding.spec)
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)

    arg_sds = tuple(sds_like(x) for x in (A, B, *targs))
    out_dir.mkdir(parents=True, exist_ok=True)
    from distributed_sddmm_tpu.ops.blocked import knob_env_defaults

    key_names = ("BENCH_LOG_M", "BENCH_NNZ_PER_ROW", "BENCH_R",
                 "BENCH_TRIALS", "BENCH_KERNEL") + tuple(
                     sorted(knob_env_defaults()))
    report = {"ok": True, "build_s": build_s, "compile_s": {}, "env": {
        k: os.environ.get(k, "") for k in key_names}}
    from distributed_sddmm_tpu.bench import aot

    for n in (1, 1 + trials):
        t0 = time.monotonic()
        compiled = bench.make_headline_chain(prog, n).lower(*arg_sds).compile()
        # Target platform, not this (CPU-pinned) process's backend: the
        # store's load-side backend gate must accept these on the chip.
        aot.save_executable(compiled, out_dir, "headline", n,
                            backend=topo.devices[0].platform)
        report["compile_s"][n] = round(time.monotonic() - t0, 1)
    (out_dir / "meta.json").write_text(json.dumps(report, indent=1))
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
