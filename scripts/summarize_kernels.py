"""Render KERNELS_TPU.jsonl (kernel_sweep.py output) into KERNELS_TPU.md.

Usage: python scripts/summarize_kernels.py [in.jsonl] [out.md]
"""

from __future__ import annotations

import json
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]


def fmt(v) -> str:
    return "-" if v is None else f"{v:.1f}"


def main() -> int:
    src = pathlib.Path(sys.argv[1] if len(sys.argv) > 1 else REPO / "KERNELS_TPU.jsonl")
    dst = pathlib.Path(sys.argv[2] if len(sys.argv) > 2 else REPO / "KERNELS_TPU.md")
    recs = []
    for l in src.read_text().splitlines():
        if not l.strip():
            continue
        try:
            recs.append(json.loads(l))
        except json.JSONDecodeError:
            # A truncated tail line is normal: producers append under
            # hard-kill timeouts.
            print(f"skipping malformed line: {l[:60]!r}", file=sys.stderr)
    if not recs:
        print("no records", file=sys.stderr)
        return 1
    n_skipped = sum(1 for r in recs if r.get("skipped"))
    recs = [r for r in recs if not r.get("skipped")]
    if n_skipped:
        print(f"dropping {n_skipped} timing-free tombstone record(s) "
              "(clamped block preference)", file=sys.stderr)
    if not recs:
        print("no measured records", file=sys.stderr)
        return 1

    # Group-probe rows (same config, varying blocks/group) vs sweep rows.
    probe = [r for r in recs if r.get("fused_only") or (
        r["kernel"].startswith("pallas") and r.get("sddmm_gflops") is None)]
    sweep = [r for r in recs if r not in probe]

    grid_points = sorted({(r["logM"], r["npr"], r["R"]) for r in recs})
    lines = [
        "# KERNELS_TPU — XLA vs Pallas local-kernel sweep (single v5e chip)",
        "",
        "Produced by `scripts/kernel_sweep.py` (resumable orchestrator over",
        "`scripts/tune_blocks.py` workers) on the tunneled TPU backend; the",
        "reference analog is `local_kernel_benchmark.cpp:276-280`. The full",
        "36-config cross product is not feasible at this backend's",
        "per-config compile cost (5-12 min each), so the PLAN",
        "(`scripts/plans/star_sweep.json`) is a star design around the",
        "center (logM=14, nnz/row=32, R=128) covering every axis value of",
        "the prescribed grid, plus the heavy corner (16, 128, 512). This",
        "file reports whatever the backend allowed so far:",
        f"**{len(grid_points)} grid point(s) measured** — "
        + ", ".join(f"({a},{b},{c})" for a, b, c in grid_points) + ".",
        "",
        "GFLOP/s = 2*nnz*R/elapsed per op; fused pair counts both ops",
        "(`benchmark_dist.cpp:147-149`).",
        "",
    ]

    if sweep:
        lines += [
            "## Star sweep",
            "",
            "| logM | nnz/row | R | kernel | blocks | group | scatter | batch | SDDMM | SpMM | fused pair |",
            "|---|---|---|---|---|---|---|---|---|---|---|",
        ]
        for r in sorted(sweep, key=lambda r: (r["logM"], r["npr"], r["R"], r["kernel"])):
            blocks = f"{r['bm']}x{r['bn']}" if "bm" in r else "-"
            form = r.get("scatter_form", "bt") if r["kernel"].startswith("pallas") else "-"
            lines.append(
                f"| {r['logM']} | {r['npr']} | {r['R']} | {r['kernel']} "
                f"| {blocks} | {r.get('group', '-')} | {form} "
                f"| {'y' if r.get('batch_step') else '-'} "
                f"| {fmt(r.get('sddmm_gflops'))} | {fmt(r.get('spmm_gflops'))} "
                f"| {fmt(r.get('fused_pair_gflops'))} |"
            )
        lines.append("")

    if probe:
        lines += [
            "## Block/group tuning probe (logM=16, nnz/row=32, R=128, fused pair)",
            "",
            "| blocks | group | scatter | batch | chunk | chunks | occupancy | ns/chunk | GFLOP/s |",
            "|---|---|---|---|---|---|---|---|---|",
        ]
        for r in sorted(probe, key=lambda r: (r.get("bm", 0), r.get("bn", 0),
                                              r.get("group", 1),
                                              r.get("scatter_form", "bt"),
                                              bool(r.get("batch_step")),
                                              r.get("chunk", 128))):
            lines.append(
                f"| {r.get('bm')}x{r.get('bn')} | {r.get('group', 1)} "
                f"| {r.get('scatter_form', 'bt')} "
                f"| {'y' if r.get('batch_step') else '-'} "
                f"| {r.get('chunk', 128)} "
                f"| {r.get('n_chunks')} | {r.get('occupancy')} "
                f"| {fmt(r.get('fused_ns_per_chunk'))} "
                f"| {fmt(r.get('fused_pair_gflops'))} |"
            )
        lines.append("")

    dst.write_text("\n".join(lines))
    print(f"wrote {dst} ({len(sweep)} sweep + {len(probe)} probe records)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
