// Native host-side data layer for distributed_sddmm_tpu.
//
// The TPU compute path is JAX/XLA/Pallas; everything that the reference
// implemented as C++ host machinery around its kernels gets a native
// equivalent here, exposed through a C ABI consumed via ctypes
// (distributed_sddmm_tpu/native.py):
//
//  * Graph500-style R-mat generation — reference used CombBLAS
//    GenGraph500Data (/root/reference/SpmatLocal.hpp:499-516).
//  * Matrix-market coordinate IO — reference used CombBLAS
//    ParallelReadMM / ParallelWriteMM (/root/reference/SpmatLocal.hpp:486-497,
//    ParIOTest.cpp:66-73).
//  * Stable bucket (counting) sort — the hot host-side op behind nonzero
//    redistribution and chunk-list construction; the reference's analog is
//    the MPI_Alltoallv shuffle + GNU parallel sort
//    (/root/reference/SpmatLocal.hpp:389-462).
//
// Build: see native/Makefile (g++ -O3 -fopenmp -shared -fPIC). The Python
// wrapper falls back to numpy implementations when the library is absent.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cctype>

#ifdef _OPENMP
#include <omp.h>
#endif

extern "C" {

// ----------------------------------------------------------------------
// splitmix64: counter-based, so edge generation is deterministic AND
// embarrassingly parallel (each edge derives its stream from seed+index).
// ----------------------------------------------------------------------
static inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

static inline double u01(uint64_t bits) {
  return (double)(bits >> 11) * (1.0 / 9007199254740992.0);  // 2^-53
}

// R-mat: recursive-quadrant edge sampling with initiator (a,b,c,d).
// rows/cols must hold n_edges int64 each.
void hnh_rmat(int64_t log_m, int64_t n_edges, double a, double b, double c,
              double d, uint64_t seed, int64_t* rows, int64_t* cols) {
  const double ab = a + b;
  const double cd = c + d;
  // P(col bit = 1 | row bit): top half -> b/(a+b), bottom half -> d/(c+d).
  const double top = ab > 0 ? b / ab : 0.0;
  const double bot = cd > 0 ? d / cd : 0.0;
#pragma omp parallel for schedule(static)
  for (int64_t e = 0; e < n_edges; ++e) {
    uint64_t st = splitmix64(seed ^ (uint64_t)e * 0x9e3779b97f4a7c15ULL);
    int64_t r = 0, cc = 0;
    for (int64_t lvl = 0; lvl < log_m; ++lvl) {
      st = splitmix64(st);
      const double u = u01(st);
      st = splitmix64(st);
      const double v = u01(st);
      const int rbit = u >= ab;
      const int cbit = v < (rbit ? bot : top);
      r = (r << 1) | rbit;
      cc = (cc << 1) | cbit;
    }
    rows[e] = r;
    cols[e] = cc;
  }
}

// ----------------------------------------------------------------------
// Stable parallel counting sort by bucket key.
// counts: [n_buckets] out. order: [n] out — argsort(keys, stable).
// ----------------------------------------------------------------------
int hnh_bucket_sort(const int64_t* keys, int64_t n, int64_t n_buckets,
                    int64_t* counts, int64_t* order) {
  int nt = 1;
#ifdef _OPENMP
  nt = omp_get_max_threads();
#endif
  // Per-thread histograms over contiguous slices keep the scatter stable.
  // Clamp threads so the histogram block stays bounded for huge key spaces.
  const int64_t kHistCap = 1LL << 31;  // 2 GiB of int64 histogram at most
  while (nt > 1 && (int64_t)nt * n_buckets * 8 > kHistCap) nt /= 2;
  int64_t* hist = (int64_t*)calloc((size_t)nt * (size_t)n_buckets, sizeof(int64_t));
  if (!hist) return -1;
#pragma omp parallel num_threads(nt)
  {
#ifdef _OPENMP
    const int t = omp_get_thread_num();
#else
    const int t = 0;
#endif
    const int64_t lo = n * t / nt, hi = n * (t + 1) / nt;
    int64_t* h = hist + (int64_t)t * n_buckets;
    for (int64_t i = lo; i < hi; ++i) ++h[keys[i]];
  }
  // Column-major exclusive prefix over (bucket, thread) gives each thread
  // its stable write base per bucket.
  int64_t run = 0;
  for (int64_t b = 0; b < n_buckets; ++b) {
    counts[b] = 0;
    for (int t = 0; t < nt; ++t) {
      const int64_t v = hist[(int64_t)t * n_buckets + b];
      hist[(int64_t)t * n_buckets + b] = run;
      run += v;
      counts[b] += v;
    }
  }
#pragma omp parallel num_threads(nt)
  {
#ifdef _OPENMP
    const int t = omp_get_thread_num();
#else
    const int t = 0;
#endif
    const int64_t lo = n * t / nt, hi = n * (t + 1) / nt;
    int64_t* h = hist + (int64_t)t * n_buckets;
    for (int64_t i = lo; i < hi; ++i) order[h[keys[i]]++] = i;
  }
  free(hist);
  return 0;
}

// ----------------------------------------------------------------------
// Matrix-market coordinate IO.
// ----------------------------------------------------------------------
// Pass 1: header + counts. Returns 0 on success, negative on error.
// symmetric: 0 = general, 1 = symmetric/hermitian (real), 2 = skew-symmetric
// (mirror entries negate). pattern: 1 if entries carry no value field.
// Complex fields and dense 'array' format return an error so the caller can
// fall back to a full-featured reader.
int hnh_mtx_header(const char* path, int64_t* M, int64_t* N, int64_t* nnz,
                   int* symmetric, int* pattern) {
  FILE* f = fopen(path, "r");
  if (!f) return -1;
  char line[1024];
  if (!fgets(line, sizeof line, f)) { fclose(f); return -2; }
  if (strncmp(line, "%%MatrixMarket", 14) != 0) { fclose(f); return -3; }
  if (strstr(line, "skew-symmetric")) {
    *symmetric = 2;
  } else if (strstr(line, "symmetric") || strstr(line, "hermitian")) {
    *symmetric = 1;
  } else {
    *symmetric = 0;
  }
  *pattern = strstr(line, "pattern") ? 1 : 0;
  if (strstr(line, "array")) { fclose(f); return -4; }  // dense not supported
  if (strstr(line, "complex")) { fclose(f); return -6; }
  while (fgets(line, sizeof line, f)) {
    if (line[0] != '%') break;
  }
  if (sscanf(line, "%ld %ld %ld", (long*)M, (long*)N, (long*)nnz) != 3) {
    fclose(f);
    return -5;
  }
  fclose(f);
  return 0;
}

// Pass 2: parse entries (1-based in file -> 0-based out). rows/cols/vals
// sized nnz (vals ignored when pattern). Returns entries read or negative.
int64_t hnh_mtx_read(const char* path, int64_t nnz, int pattern, int64_t* rows,
                     int64_t* cols, double* vals) {
  FILE* f = fopen(path, "r");
  if (!f) return -1;
  char line[1024];
  // Skip header + comments + size line.
  if (!fgets(line, sizeof line, f)) { fclose(f); return -2; }
  while (fgets(line, sizeof line, f)) {
    if (line[0] != '%') break;  // size line consumed
  }
  int64_t k = 0;
  while (k < nnz && fgets(line, sizeof line, f)) {
    char* p = line;
    char* q = p;
    const long r = strtol(q, &q, 10);
    if (q == p) continue;  // blank/comment line
    if (*q && !isspace((unsigned char)*q)) continue;  // '2.5'-style index
    char* q2 = q;
    const long c = strtol(q2, &q2, 10);
    if (q2 == q) continue;  // missing column field
    if (*q2 && !isspace((unsigned char)*q2)) continue;
    double v = 1.0;
    if (!pattern) {
      // A malformed value field ("bogus", missing) used to load
      // silently as 0.0; skipping it instead makes the parsed count
      // fall short of the header and the caller raise -- the same
      // fail-loudly contract the partitioned loader enforces.
      char* q3 = q2;
      v = strtod(q3, &q3);
      if (q3 == q2) continue;
    }
    rows[k] = r - 1;
    cols[k] = c - 1;
    vals[k] = v;
    ++k;
  }
  fclose(f);
  return k;
}

// Parse whitespace-separated coordinate triplets (or pairs, for
// pattern files) from an in-memory buffer: one line per entry, 1-based
// indices on disk -> 0-based out. Blank (whitespace-only) lines are
// skipped; a NON-blank line that does not parse into the expected
// fields within its own newline counts into *n_bad and is skipped --
// the Python layer raises on n_bad like np.loadtxt would, so the
// native and numpy chunk parsers stay strictness-identical. Returns
// entries written (<= cap).
//
// This is the partitioned loader's chunk parser (dist/ingest.py): the
// ctypes call releases the GIL, so a thread pool over byte-range
// chunks parses in genuine parallel -- the numpy text readers hold the
// GIL and cannot.
int64_t hnh_parse_triplets(const char* buf, int64_t len, int pattern,
                           int64_t cap, int64_t* rows, int64_t* cols,
                           double* vals, int64_t* n_bad) {
  const char* p = buf;
  const char* end = buf + len;
  int64_t k = 0;
  int64_t bad = 0;
  while (p < end && k < cap) {
    const char* nl = (const char*)memchr(p, '\n', (size_t)(end - p));
    const char* line_end = nl ? nl : end;
    int blank = 1;
    for (const char* s = p; s < line_end; ++s) {
      if (!isspace((unsigned char)*s)) { blank = 0; break; }
    }
    const char* first = p;
    while (first < line_end && isspace((unsigned char)*first)) ++first;
    if (first < line_end && *first == '%') {
      // Interior comment line -- legal in the wild and skipped by the
      // whole-matrix loader; not data, not malformed.
      p = nl ? nl + 1 : end;
      continue;
    }
    if (!blank) {
      int ok = 0;
      char* q = (char*)p;
      const long r = strtol(q, &q, 10);
      // Index fields must end at a whitespace boundary: '2.5' must
      // not truncate-parse as 2 with '.5' bleeding into the next
      // field (the python fallback rejects such lines; the two
      // parsers must agree line for line).
      if (q != p && q <= line_end
          && (q == line_end || isspace((unsigned char)*q))) {
        char* q2 = q;
        const long c = strtol(q2, &q2, 10);
        if (q2 != q && q2 <= line_end
            && (q2 == line_end || isspace((unsigned char)*q2))) {
          double v = 1.0;
          int vok = 1;
          if (!pattern) {
            char* q3 = q2;
            v = strtod(q3, &q3);
            vok = (q3 != q2 && q3 <= line_end);
            q2 = vok ? q3 : q2;
          }
          if (vok) {
            // Extra NUMERIC fields are legal (the numpy fallback
            // slices them away); non-numeric residue (e.g. "3.5xx"
            // leaves "xx") is what numpy would reject.
            int trailing = 0;
            char* s = q2;
            while (s < line_end) {
              while (s < line_end && isspace((unsigned char)*s)) ++s;
              if (s >= line_end) break;
              char* s2 = s;
              strtod(s, &s2);
              if (s2 == s || s2 > line_end) { trailing = 1; break; }
              s = s2;
            }
            if (!trailing) {
              rows[k] = r - 1;
              cols[k] = c - 1;
              vals[k] = v;
              ++k;
              ok = 1;
            }
          }
        }
      }
      if (!ok) ++bad;
    }
    p = nl ? nl + 1 : end;
  }
  if (n_bad) *n_bad = bad;
  return k;
}

int64_t hnh_mtx_write(const char* path, int64_t M, int64_t N, int64_t nnz,
                      const int64_t* rows, const int64_t* cols,
                      const double* vals) {
  FILE* f = fopen(path, "w");
  if (!f) return -1;
  int ok = fprintf(f, "%%%%MatrixMarket matrix coordinate real general\n") >= 0;
  ok = ok && fprintf(f, "%ld %ld %ld\n", (long)M, (long)N, (long)nnz) >= 0;
  for (int64_t k = 0; ok && k < nnz; ++k) {
    ok = fprintf(f, "%ld %ld %.17g\n", (long)(rows[k] + 1), (long)(cols[k] + 1),
                 vals[k]) >= 0;
  }
  // fclose flushes buffered data; a failure there (ENOSPC, I/O error) means
  // the file on disk is truncated even if every fprintf "succeeded".
  if (fclose(f) != 0) ok = 0;
  return ok ? nnz : -2;
}

int hnh_num_threads(void) {
#ifdef _OPENMP
  return omp_get_max_threads();
#else
  return 1;
#endif
}

}  // extern "C"
